"""TPM101 good: the timed region blocks on the op before reading the
clock (the reference's kernel-then-synchronize discipline)."""

import time

import jax.numpy as jnp

from tpu_mpi_tests.instrument.timers import block


def timed_daxpy(a, x, y):
    t0 = time.perf_counter()
    out = block(jnp.add(a * x, y))
    seconds = time.perf_counter() - t0
    return out, seconds
