"""TPM601 bad: the timer thread and the main thread write the same
handle with no lock — records interleave (the watchdog JSONL bug)."""

import threading


class Recorder:
    def __init__(self, path):
        self._f = open(path, "a")

    def arm(self, seconds):
        threading.Timer(seconds, self._dump).start()

    def _dump(self):
        self._f.write("timer fired\n")

    def record(self, line):
        self._f.write(line + "\n")
