"""TPM1603 good: the arm/disarm idiom — install() rebinds the slot,
uninstall() puts ``None`` back, both in the same layer."""

from plane import slots


def install(tracer):
    slots._TRACE_HOOK = _make(tracer)


def uninstall():
    slots._TRACE_HOOK = None


def _make(tracer):
    def hook(op):
        tracer.append(op)
    return hook
