"""TPM1601 good: EVERY path into the shared write holds the lock —
the Timer-side ``poll`` takes it too, so the caller-lockset
intersection keeps the helper's write protected."""

import threading


class Recorder:
    def __init__(self, path):
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def record(self, line):
        with self._lock:
            self._append(line)

    def _append(self, line):
        self._f.write(line + "\n")

    def poll(self):
        with self._lock:
            self._append("poll")
