"""The hook slot and its reader (identical to the bad tree's)."""

_TRACE_HOOK = None


def fire(op):
    hook = _TRACE_HOOK
    if hook is not None:
        hook(op)
