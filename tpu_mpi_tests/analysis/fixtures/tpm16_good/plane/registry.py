"""TPM1602 good: the re-entered lock is an RLock — re-acquisition on
the same thread is the documented, sanctioned shape."""

import threading


class Gauges:
    def __init__(self):
        self._lock = threading.RLock()
        self._vals = {}

    def bump(self, key):
        with self._lock:
            self._vals[key] = self._vals.get(key, 0) + 1
            self._flush_locked()

    def _flush_locked(self):
        with self._lock:
            self._vals.clear()
