"""Same thread entry as the bad tree — the fix is on the lock side,
not the spawn side."""

import threading

from plane.recorder import Recorder


def launch(path):
    r = Recorder(path)
    threading.Timer(1.0, r.poll).start()
    return r
