"""TPM1201 good: the in-place idiom — the result is rebound to the
donated name, so every later read sees the live replacement buffer."""

from dnt.helper import reduce_into


def step(x, mesh):
    x = reduce_into(x, mesh)
    return x * 2.0
