"""One helper level: a param forwarded into a donated position of the
callee is effectively donated here too (summary composition)."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def reduce_into(buf, mesh):
    return allreduce_sum(buf, mesh)
