"""TPM601 suppressed: the timer is cancelled before any main-thread
write, so the handle is never actually contended."""

import threading


class Recorder:
    def __init__(self, path):
        self._f = open(path, "a")
        self._timer = threading.Timer(3600.0, self._f.flush)

    def record(self, line):
        self._timer.cancel()
        self._f.write(line + "\n")  # tpumt: ignore[TPM601]
