"""TPM1101 good: the collective runs on every rank; the rank branch
only prints — both paths dispatch the same (empty) collective
sequence."""

from jax import process_index

from spmd.comms import global_sum


def step(x, mesh):
    x = global_sum(x, mesh)
    if process_index() == 0:
        print("step done")
    return x
