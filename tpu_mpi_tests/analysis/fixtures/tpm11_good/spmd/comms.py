"""Helper whose call graph dispatches a collective — fine when every
rank calls it unconditionally."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def global_sum(x, mesh):
    return allreduce_sum(x, mesh)
