"""TPM1601 suppressed: same shape as the bad tree, silenced with a
why-comment — the stand-in for a sanctioned ordering argument."""

import threading


class Recorder:
    def __init__(self, path):
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def record(self, line):
        with self._lock:
            self._append(line)

    def _append(self, line):
        # pretend-benign: the timer is cancelled before record() runs
        self._f.write(line + "\n")  # tpumt: ignore[TPM1601]

    def poll(self):
        self._append("poll")
