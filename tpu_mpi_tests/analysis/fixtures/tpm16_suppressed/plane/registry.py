"""TPM1602 suppressed: both the lock-held call and the helper's
re-acquire carry the inline suppression."""

import threading


class Gauges:
    def __init__(self):
        self._lock = threading.Lock()
        self._vals = {}

    def bump(self, key):
        with self._lock:
            self._vals[key] = self._vals.get(key, 0) + 1
            self._flush_locked()  # tpumt: ignore[TPM1602]

    def _flush_locked(self):
        with self._lock:  # tpumt: ignore[TPM1602]
            self._vals.clear()
