"""Same thread entry as the bad tree."""

import threading

from plane.recorder import Recorder


def launch(path):
    r = Recorder(path)
    threading.Timer(1.0, r.poll).start()
    return r
