"""TPM1603 suppressed: the disarm lives in another layer by design —
the rebind carries the sanctioned inline suppression."""

from plane import slots


def install(tracer):
    slots._TRACE_HOOK = _make(tracer)  # tpumt: ignore[TPM1603]


def _make(tracer):
    def hook(op):
        tracer.append(op)
    return hook
