"""Helper whose call graph dispatches a collective — the divergence
check must see through this frame via the project summaries."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def global_sum(x, mesh):
    return allreduce_sum(x, mesh)
