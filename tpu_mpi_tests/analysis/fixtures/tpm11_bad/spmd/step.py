"""TPM1101 bad: only rank 0 enters the collective (through a helper) —
the other ranks never arrive and the mesh deadlocks."""

from jax import process_index

from spmd.comms import global_sum


def step(x, mesh):
    if process_index() == 0:
        x = global_sum(x, mesh)
    return x
