"""TPM1101 suppressed: the sanctioned rank-0-only shape — this step
runs under a single-process tune sweep where no sibling rank exists to
deadlock against, and the suppression's why-comment says so."""

from jax import process_index

from spmd.comms import global_sum


def step(x, mesh):
    # single-process sweep: rank 0 IS the whole mesh here
    if process_index() == 0:  # tpumt: ignore[TPM1101]
        x = global_sum(x, mesh)
    return x
