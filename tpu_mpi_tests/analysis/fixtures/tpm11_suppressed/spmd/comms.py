"""Helper whose call graph dispatches a collective (suppressed tree)."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def global_sum(x, mesh):
    return allreduce_sum(x, mesh)
