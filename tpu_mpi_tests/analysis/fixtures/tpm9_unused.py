"""TPM900: a suppression whose finding is gone must itself be flagged."""

x = 1  # tpumt: ignore[TPM101]
