"""TPM10xx bad: chaos fault injection reachable from driver-shaped
code — an armed kill hook shipping inside a hot path."""

from tpu_mpi_tests import chaos
from tpu_mpi_tests.chaos import inject


def run(args):
    # lazy import is just as reachable — import timing is not the point
    from tpu_mpi_tests.chaos.inject import arm_from_spec

    arm_from_spec("kill:rank=1:op=allreduce", rank=0)
    inject.disarm()
    return chaos.armed()
