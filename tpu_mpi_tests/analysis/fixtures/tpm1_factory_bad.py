"""TPM101 bad: the clock pair times a compiled-fn-FACTORY result.

The fused-tier runner (``iterate_fused_rdma_fn``, ISSUE 15) is a
compiled-fn factory like ``pick_kernel_tier``: its return value
dispatches async device work when called. The dynamic module handle
defeats import-graph origin resolution, so conviction rests on the
FACTORY_NAMES list (analysis/core.py) alone — the shape this fixture
pins.
"""

import importlib
import time

H = importlib.import_module("tpu_mpi_tests.comm.halo")


def timed_fused_iterate(mesh, z):
    run = H.iterate_fused_rdma_fn(mesh, "shard", 2, 1e-2)
    t0 = time.perf_counter()
    out = run(z, 8)
    seconds = time.perf_counter() - t0
    return out, seconds
