"""Helper that imports jax lazily, inside the function that needs it."""


def mean(xs):
    import jax.numpy as jnp

    return jnp.mean(jnp.asarray(xs, jnp.float32))
