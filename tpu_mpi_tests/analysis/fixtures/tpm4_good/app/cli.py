"""Entry point that must stay importable without jax."""

from app import helpers


def main():
    return helpers.mean([1, 2, 3])
