"""TPM1301 suppressed: the sanctioned single-process site — this entry
point is only reachable from the one-process sweep driver, where rank 0
is the whole fleet and the placeholder arm is dead code; the
suppression's why-comment says so."""

from jax import process_index


def tune_and_apply(sweep, apply_schedule, space, x):
    if process_index() == 0:
        winner = sweep(space)
    else:
        winner = None
    # single-process driver: no sibling rank ever reads the None arm
    return apply_schedule(x, winner)  # tpumt: ignore[TPM1301]
