"""TPM14xx suppressed: a consumer deliberately reading ahead of its
producer — the field and kind land with the NEXT producer release, and
the why-comments say so (forward-compat reads are the one sanctioned
drift direction: the .get default is the documented fallback)."""


def emit_probe(sink, t, v):
    sink({"kind": "probe", "event": "sample", "t": t, "value": v})


def probe_values(records):
    out = []
    for rec in records:
        if rec.get("kind") == "probe":
            # v2 producers add calibrated values; default until then
            out.append(rec.get("val", 0.0))  # tpumt: ignore[TPM1401]
    return out


def count_v2(records):
    n = 0
    for rec in records:
        # the v2 stream lands with the next producer release
        if rec.get("kind") == "probe_v2":  # tpumt: ignore[TPM1402]
            n += 1
    return n
