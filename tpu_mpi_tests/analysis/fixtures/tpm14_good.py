"""TPM14xx good: the consumer reads exactly what the producer emits
and filters only on kinds that exist — the contract the generated
RECORDS.md table documents."""


def emit_probe(sink, t, v):
    sink({"kind": "probe", "event": "sample", "t": t, "value": v})


def probe_values(records):
    out = []
    for rec in records:
        if rec.get("kind") == "probe":
            out.append(rec.get("value"))
    return out
