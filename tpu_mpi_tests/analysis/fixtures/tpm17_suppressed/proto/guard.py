"""TPM1703 suppressed: the swallowing handler, sanctioned with a
why-comment (the raiser is environmental and symmetric on all ranks)."""

from proto.comms import global_sum


def reduce_or_skip(x, mesh):
    out = x
    try:  # tpumt: ignore[TPM1703] — raiser is symmetric (import error)
        out = global_sum(x, mesh)
    except Exception:
        pass
    return out
