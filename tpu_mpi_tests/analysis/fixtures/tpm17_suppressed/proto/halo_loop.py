"""TPM1702 suppressed: the rank-dependent trip count, sanctioned with
a why-comment (a deliberately-staggered drain in a chaos test)."""

from jax import process_index

from proto.comms import global_sum


def drain(x, mesh, n):
    for _ in range(n - process_index()):  # tpumt: ignore[TPM1702] — chaos drain
        x = global_sum(x, mesh)
    return x
