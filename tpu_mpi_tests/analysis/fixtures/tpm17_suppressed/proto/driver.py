"""TPM1701 suppressed: the rank-guarded handshake, sanctioned with a
why-comment (a single-process harness where only rank 0 exists)."""

from jax import process_index

from proto.comms import fanout


def open_sweep(value):
    if process_index() == 0:  # tpumt: ignore[TPM1701] — 1-proc harness
        fanout(value, "sweep:open")
    return value
