"""TPM17xx suppressed tree: the bad shapes with sanctioned
``# tpumt: ignore[...]`` why-comments — each must silence exactly its
finding (an unused suppression is itself a TPM900 finding)."""
