"""TPM10xx good: production code never touches the chaos package —
observability hooks are rebound BY chaos at arm time, so the clean
shape here is plain telemetry with no chaos import at all."""

from tpu_mpi_tests.instrument import telemetry


def run(args):
    with telemetry.comm_span("allreduce", nbytes=1024):
        pass
    return 0
