"""TPM3xx bad: a width-ambiguous float literal and an epoch crossing
the device boundary (the PR 2 clock-sync quantization bug shape)."""

import time

import jax.numpy as jnp
from jax.experimental import multihost_utils


def record_clock():
    scale = jnp.asarray(2.5)
    stamp = multihost_utils.process_allgather(time.time())
    return scale, stamp
