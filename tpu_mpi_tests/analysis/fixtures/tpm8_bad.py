"""TPM8 bad fixture: a sync between prefetch issue and consume point
re-serializes the pipeline — the in-flight exchange drains against the
block instead of hiding under the compute."""
import jax

from tpu_mpi_tests.instrument.telemetry import async_span
from tpu_mpi_tests.instrument.timers import block


def pipelined_step(exchange_fn, core_fn, z, other):
    h = async_span("halo_exchange", nbytes=1024)
    ex = exchange_fn(z)
    jax.block_until_ready(other)  # BAD: drains the queue mid-region
    out = core_fn(z)
    h.done(ex)
    return ex, out


def pipelined_step_block(exchange_fn, core_fn, z):
    h = async_span("halo_exchange")
    ex = exchange_fn(z)
    out = block(core_fn(z))  # BAD (unsuppressed): lexically in-region
    h.done(ex)
    return ex, out
