"""TPM1201 suppressed: this probe reads the donated buffer ON PURPOSE —
it exists to demonstrate the use-after-donate failure mode, and the
why-comment says so."""

from dnt.helper import reduce_into


def step(x, mesh):
    total = reduce_into(x, mesh)
    # the MPI_IN_PLACE-style probe: touching the deleted buffer IS the demo
    return x + total  # tpumt: ignore[TPM1201]
