"""One helper level (suppressed tree): forwarding into a donated
position donates here too."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def reduce_into(buf, mesh):
    return allreduce_sum(buf, mesh)
