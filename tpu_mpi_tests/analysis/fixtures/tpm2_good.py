"""TPM201 good: in-trace printing goes through jax.debug.print, and
host-side records are guarded by the trace check telemetry.py uses."""

import jax


def _under_trace():
    from jax import core

    return not core.trace_state_clean()


@jax.jit
def step(x):
    jax.debug.print("stepping {}", x)
    return x + 1


def record(rep, x):
    if not _under_trace():
        rep.line("STEP")
    return step(x)
