"""TPM101 bad: the clock pair times an async dispatch, not the compute."""

import time

import jax.numpy as jnp


def timed_daxpy(a, x, y):
    t0 = time.perf_counter()
    out = jnp.add(a * x, y)
    seconds = time.perf_counter() - t0
    return out, seconds
