"""TPM501 good: the collective axis matches the shard_map binding."""

from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_mpi_tests.compat import shard_map


def total(mesh, x):
    def body(v):
        return lax.psum(v, "shard")

    return shard_map(
        body, mesh=mesh, in_specs=P("shard"), out_specs=P()
    )(x)
