"""TPM7xx suppressed: a deliberate pin with its why. A reference-parity
A/B needs the frozen round-5 value regardless of what the schedule
cache holds — tuning it away would change what the comparison measures."""

LEGACY_K_TILE = 2048  # tpumt: ignore[TPM701]
