"""TPM14xx bad: the record contract drifted in both directions — a
consumer reads a field its kind's producer never emits (TPM1401: the
``.get`` default is served forever and the table silently zeroes), and
another consumer filters on a kind nothing produces (TPM1402: its rows
can never exist)."""


def emit_probe(sink, t, v):
    sink({"kind": "probe", "event": "sample", "t": t, "value": v})


def probe_values(records):
    out = []
    for rec in records:
        if rec.get("kind") == "probe":
            out.append(rec.get("val"))
    return out


def count_v2(records):
    n = 0
    for rec in records:
        if rec.get("kind") == "probe_v2":
            n += 1
    return n
