"""TPM1703 bad: the collective is reachable under an exception path
whose handler swallows and continues — the rank that catches skips the
partner op the other ranks are blocking in."""

from proto.comms import global_sum


def reduce_or_skip(x, mesh):
    out = x
    try:
        out = global_sum(x, mesh)
    except Exception:
        pass
    return out
