"""TPM1702 bad: the trip count is a function of the rank, and the loop
body dispatches a collective — every rank agrees on every op yet runs
a different *count* of them, so some rank enters an iteration its
partners never will (the divergent-loop deadlock)."""

from jax import process_index

from proto.comms import global_sum


def drain(x, mesh, n):
    for _ in range(n - process_index()):
        x = global_sum(x, mesh)
    return x
