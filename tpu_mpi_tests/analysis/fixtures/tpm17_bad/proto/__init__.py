"""TPM17xx bad tree: every file's branches look locally symmetric to
the per-branch TPM11xx rules — the deadlocks only exist in the
*composed* whole-program schedule the protocol verifier builds."""
