"""TPM1701 bad: only rank 0 runs the broadcast handshake. Each branch
is clean to TPM1101 (no core collective diverges) and to TPM1301 (the
call binds nothing) — the hang is only visible in the composed
schedule: rank 0's stream is [bcast], everyone else's is []."""

from jax import process_index

from proto.comms import fanout


def open_sweep(value):
    if process_index() == 0:
        fanout(value, "sweep:open")
    return value
