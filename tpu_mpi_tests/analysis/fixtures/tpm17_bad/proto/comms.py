"""Wrappers: the collective, its telemetry emitter (the runtime
alphabet the schedule automaton derives), and the broadcast-class
handshake TPM1101's alphabet deliberately excludes."""

from tpu_mpi_tests.comm.collectives import allreduce_sum
from tpu_mpi_tests.instrument.telemetry import comm_span
from tpu_mpi_tests.tune.fleet import bcast


def global_sum(x, mesh):
    with comm_span("allreduce", axis_name="shard"):
        return allreduce_sum(x, mesh)


def fanout(value, tag):
    return bcast(value, tag)
