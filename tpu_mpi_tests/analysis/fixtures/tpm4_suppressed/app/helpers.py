"""Helper whose eager jax import is acknowledged (e.g. a module being
migrated to the lazy idiom)."""

import jax.numpy as jnp  # tpumt: ignore[TPM401]


def mean(xs):
    return jnp.mean(jnp.asarray(xs, jnp.float32))
