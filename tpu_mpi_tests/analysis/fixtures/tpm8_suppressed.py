"""TPM8 suppressed fixture: the ONE sanctioned in-region sync — the
overlapped compute itself must block under its phase bracket (that is
the window the exchange hides beneath), and says so."""
from tpu_mpi_tests.instrument.telemetry import async_span
from tpu_mpi_tests.instrument.timers import block


def pipelined_step(exchange_fn, core_fn, z):
    h = async_span("halo_exchange", nbytes=1024)
    ex = exchange_fn(z)
    # the overlapped interior compute IS the measured phase — blocking
    # on it is the design, not a re-serialization
    out = block(core_fn(z))  # tpumt: ignore[TPM801]
    h.done(ex)
    return ex, out
