"""TPM17xx good tree: the same program shapes with the protocol
discipline applied — every rank emits the identical composed schedule,
rank branches carry no events, loop bounds are replicated, and the
exception path re-raises instead of skipping its partner op."""
