"""TPM1702 good: the trip count is a replicated value — every rank
executes the same number of collective iterations."""

from proto.comms import global_sum


def drain(x, mesh, n):
    for _ in range(n):
        x = global_sum(x, mesh)
    return x
