"""TPM1701 good: every rank runs the broadcast handshake; the
rank-guarded branch carries no collective/broadcast events, so the
composed schedule is identical on both paths."""

from jax import process_index

from proto.comms import fanout


def open_sweep(value):
    value = fanout(value, "sweep:open")
    if process_index() == 0:
        print("sweep opened")
    return value
