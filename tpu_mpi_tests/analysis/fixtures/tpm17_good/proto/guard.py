"""TPM1703 good: the handler re-raises — the sanctioned abort shape.
No rank quietly continues past a collective its partners entered."""

from proto.comms import global_sum


def reduce_or_skip(x, mesh):
    try:
        return global_sum(x, mesh)
    except Exception:
        raise
