"""TPM8 good fixture: syncs happen OUTSIDE the overlap region — before
the prefetch issues or after the handle is consumed."""
import jax

from tpu_mpi_tests.instrument.telemetry import async_span
from tpu_mpi_tests.instrument.timers import block


def pipelined_step(exchange_fn, core_fn, z, other):
    jax.block_until_ready(other)  # before the region opens: fine
    h = async_span("halo_exchange", nbytes=1024)
    ex = exchange_fn(z)
    out = core_fn(z)
    h.done(ex)
    return ex, block(out)  # after the consume point: fine
