"""Helper that eagerly imports jax at module level (the hazard)."""

import jax.numpy as jnp


def mean(xs):
    return jnp.mean(jnp.asarray(xs, jnp.float32))
