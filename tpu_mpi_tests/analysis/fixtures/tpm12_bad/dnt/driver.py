"""TPM1201 bad: x is donated through reduce_into (one helper level —
allreduce_sum donates position 0) and read again afterwards: the buffer
is already deleted."""

from dnt.helper import reduce_into


def step(x, mesh):
    total = reduce_into(x, mesh)
    return x + total
