"""TPM1102 bad: every non-zero rank leaves the function before the
collective — only rank 0 arrives at the allreduce and the mesh
deadlocks. The ISSUE-10 lexical engine compared the two BRANCH BODIES
(both collective-free here) and shipped this exact shape as a
documented false negative; the CFG engine sees the ``return`` as an
exit edge, so the continuing path's allreduce is missing from the
guarded path's sequence."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def global_mean(x, mesh, rank, world):
    if rank != 0:
        return x
    total = allreduce_sum(x, mesh)
    return total / world
