"""TPM7xx bad: hand-pinned numeric schedule constants outside the
tuner — one machine's measured optimum frozen for every topology (the
pre-autotuner MEASURED_BEST_* shape)."""

MEASURED_BEST_TILE = {"contig": 2048, "striped": 256}
HALO_BLOCK_COUNT = 2
_STREAM_STEPS_DEFAULT = 4
