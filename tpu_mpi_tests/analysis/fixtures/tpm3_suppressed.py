"""TPM3xx suppressed: a coarse timestamp where ~128 s error is fine."""

import time

import jax.numpy as jnp


def coarse_epoch():
    scale = jnp.asarray(2.5)  # tpumt: ignore[TPM301]
    stamp = jnp.asarray(time.time())  # tpumt: ignore[TPM302]
    return scale, stamp
