"""TPM3xx good: explicit dtype on the literal; the epoch crosses as
f32-exact integer microsecond digits (manifest._split_us discipline)."""

import time

import jax.numpy as jnp
from jax.experimental import multihost_utils

from tpu_mpi_tests.instrument.manifest import _join_us, _split_us


def record_clock():
    scale = jnp.asarray(2.5, jnp.float32)
    digits = multihost_utils.process_allgather(_split_us(time.time()))
    return scale, _join_us(digits)
