"""TPM101 suppressed: dispatch-only timing is the demo's point here."""

import time

import jax.numpy as jnp


def dispatch_cost(a, x, y):
    t0 = time.perf_counter()
    out = jnp.add(a * x, y)  # tpumt: ignore[TPM101]
    seconds = time.perf_counter() - t0
    return out, seconds
