"""TPM10xx suppressed: a sanctioned embedder arming chaos outside
make_reporter, with its why stated — e.g. a standalone soak harness
that owns its own reporter wiring."""

from tpu_mpi_tests.chaos import arm_from_spec  # tpumt: ignore[TPM1001]


def soak(spec, rank):
    return arm_from_spec(spec, rank)  # tpumt: ignore[TPM1001]
