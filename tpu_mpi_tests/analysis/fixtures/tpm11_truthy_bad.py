"""TPM1101 regression goldens for the lexical engine's documented
false negatives (the ROADMAP carry-over nits, closed by the ISSUE-12
CFG engine).

Two shapes the PR-10 ``_rank_dependent`` could not see — it only
matched Compare nodes whose side was a rank-NAMED variable:

* a truthiness rank test (``if not rank:`` — no Compare node at all);
* the rank stored in an arbitrarily-named local (``r = process_index()``)
  and compared later (``r == 0`` — a Compare, but against a name the
  lexical vocabulary did not know).

Both deadlock identically to the canonical ``rank == 0`` guard: only
rank 0 enters the allreduce.
"""

from jax import process_index

from tpu_mpi_tests.comm.collectives import allreduce_sum


def truthy_guard(x, mesh):
    rank = process_index()
    if not rank:
        x = allreduce_sum(x, mesh)
    return x


def alias_guard(x, mesh):
    r = process_index()
    if r == 0:
        x = allreduce_sum(x, mesh)
    return x
