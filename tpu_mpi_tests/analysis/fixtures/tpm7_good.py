"""TPM7xx good: the knob routes through the tuner. Numeric candidates
appear only inside the ``declare_space`` registration (the sanctioned
way to state a space where the knob lives), reads go through
``resolve`` (explicit > cached > prior), and schedule-named constants
without numeric values (pure config strings) are out of scope."""

from tpu_mpi_tests.tune import priors
from tpu_mpi_tests.tune.registry import declare_space, resolve

DEMO_TILE_SPACE = declare_space(
    "demo/tile",
    ({"k_tile": priors.MEASURED_BEST_K_TILE["contig"]}, {"k_tile": 512}),
    describe="demo tile space: prior first, alternative second",
)

DEFAULT_STAGING = "direct"  # string config, not a numeric schedule pin


def pick_tile(explicit=None):
    return resolve(
        "demo/tile", explicit=explicit, prior=DEMO_TILE_SPACE.prior
    )
