"""TPM1102 suppressed: the sanctioned single-process shape — this
helper only ever runs under the one-process tune sweep, where no
sibling rank exists to deadlock against, and the suppression's
why-comment says so."""

from tpu_mpi_tests.comm.collectives import allreduce_sum


def global_mean(x, mesh, rank, world):
    # single-process sweep entry: rank 0 IS the whole mesh here
    if rank != 0:  # tpumt: ignore[TPM1102]
        return x
    total = allreduce_sum(x, mesh)
    return total / world
