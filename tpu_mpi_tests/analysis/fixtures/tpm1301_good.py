"""TPM1301 good: the rank-0 winner passes through a broadcast-class
collective before any rank acts on it — every rank applies the same
replicated value, which is the SPMD-honest fleet-tuning shape."""

from jax import process_index
from jax.experimental.multihost_utils import broadcast_one_to_all


def tune_and_apply(sweep, apply_schedule, space, x):
    if process_index() == 0:
        winner = sweep(space)
    else:
        winner = None
    winner = broadcast_one_to_all(winner)
    return apply_schedule(x, winner)
