"""TPM601 good: every write of the shared handle holds the lock, one
write per record (the Reporter.jsonl discipline)."""

import threading


class Recorder:
    def __init__(self, path):
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def arm(self, seconds):
        threading.Timer(seconds, self._dump).start()

    def _dump(self):
        with self._lock:
            self._f.write("timer fired\n")

    def record(self, line):
        with self._lock:
            self._f.write(line + "\n")
