"""TPM201 bad: host side effects inside a jitted function run once at
trace time (and a reporter record there fabricates telemetry)."""

import time

import jax


@jax.jit
def step(x, rep):
    print("stepping", time.time())
    rep.line("STEP")
    return x + 1
