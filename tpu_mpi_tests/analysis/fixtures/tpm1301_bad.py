"""TPM1301 bad: the rank-0-only sweep's winner is applied by EVERY
rank without a broadcast — rank 0 applies the measured schedule while
the other ranks apply the ``None`` placeholder, and the fleet silently
diverges (the exact hazard ROADMAP item 1(a)'s fleet tuning must not
write). The ``winner = None`` arm is not a binding: it is the absence
of the value."""

from jax import process_index


def tune_and_apply(sweep, apply_schedule, space, x):
    if process_index() == 0:
        winner = sweep(space)
    else:
        winner = None
    return apply_schedule(x, winner)
