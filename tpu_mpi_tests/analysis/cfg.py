"""Per-function control-flow graphs for the flow-sensitive analyses
(ISSUE 12 tentpole).

The ISSUE-10 engine summarized branches *lexically*: an ``if`` fact
carried the events of its two statement lists and nothing else, so a
``return`` inside a rank guard was invisible — the events after the
branch were attributed to both paths even when one of them had already
left the function. That is exactly the TPM1101 false-negative class the
ROADMAP carried over (``if rank != 0: return`` before a collective).

This module builds a small, conservative CFG per function body:

* **Blocks** hold straight-line *units* — simple statements plus the
  branch/loop test expressions — in document order. Compound statements
  (``if``/``for``/``while``/``with``/``try``/``match``) are decomposed
  into blocks and edges; nested ``def``/``lambda`` bodies are other
  scopes and contribute nothing.
* **Edges** model fallthrough, branch splits/joins, loop back-edges
  (marked, so forward traversals unroll each loop once), ``break`` /
  ``continue``, and ``return``/``raise`` exits to the synthetic exit
  block.
* **Branches** record, for every ``if``, the two path entry blocks and
  whether each side's straight-line flow *terminates* (cannot fall
  through to the join) — the "early exit" bit TPM1102 keys on.
* **With regions** (ISSUE 13) record, for every ``with`` statement, the
  set of blocks its body occupies. A ``with`` opens a fresh block and
  closes into a fresh block, so region membership is whole-block — the
  lockset layer (:mod:`tpu_mpi_tests.analysis.locks`) maps each
  statement's held-lock set straight off the blocks that contain it,
  nested regions unioning naturally.

Approximations (documented in README "Static analysis"): exception
edges are not modeled — ``except`` handler bodies fork from the block
*before* the ``try`` and rejoin after it, ``finally`` runs on the
fallthrough path only, and a ``raise`` always exits the function even
when an enclosing handler would catch it. Loop ``else`` clauses run on
the fallthrough path. These keep the graph linear in the function size
while staying truthful for the SPMD shapes the rules judge.

Stdlib-only by contract, like the rest of the analysis package.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field


class Block:
    """Straight-line code: ``units`` are simple statements and test/iter
    expressions, in document order; ``succs`` are ``(block, is_back)``
    edges."""

    __slots__ = ("idx", "units", "succs")

    def __init__(self, idx: int):
        self.idx = idx
        self.units: list[ast.AST] = []
        self.succs: list[tuple["Block", bool]] = []

    def __repr__(self) -> str:  # debug aid only
        return f"<Block {self.idx} units={len(self.units)} " \
               f"succs={[s.idx for s, _ in self.succs]}>"


@dataclass
class Branch:
    """One ``if`` statement as seen by the CFG: the path entry blocks
    plus the early-exit bits. ``else_entry`` is the join block when the
    ``if`` has no ``else``."""

    node: ast.If
    then_entry: Block
    else_entry: Block
    then_exits: bool
    else_exits: bool


@dataclass
class WithRegion:
    """One ``with`` statement: its ``withitem`` context expressions and
    the block indices its body occupies (nested compounds included)."""

    node: ast.With | ast.AsyncWith
    blocks: frozenset[int]


@dataclass
class CFG:
    entry: Block
    exit: Block
    blocks: list[Block] = field(default_factory=list)
    branches: list[Branch] = field(default_factory=list)
    with_regions: list[WithRegion] = field(default_factory=list)

    def reachable(self, start: Block) -> list[Block]:
        """Blocks reachable from ``start`` (inclusive) following FORWARD
        edges only — back edges are cut, so each loop contributes its
        body once. Returned in block-creation order, which tracks
        document order closely enough for stable event sequences."""
        seen: set[int] = set()
        stack = [start]
        while stack:
            b = stack.pop()
            if b.idx in seen:
                continue
            seen.add(b.idx)
            for s, back in b.succs:
                if not back and s.idx not in seen:
                    stack.append(s)
        return sorted(
            (b for b in self.blocks if b.idx in seen),
            key=lambda b: b.idx,
        )


class _Builder:
    def __init__(self) -> None:
        self.blocks: list[Block] = []
        self.exit = self._new()
        self.cur: Block | None = self._new()
        self.entry = self.cur
        self.branches: list[Branch] = []
        self.with_regions: list[WithRegion] = []
        # innermost-first (header, after) targets for continue/break
        self.loops: list[tuple[Block, Block]] = []

    def _new(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    @staticmethod
    def _edge(a: Block, b: Block, back: bool = False) -> None:
        a.succs.append((b, back))

    def _live(self) -> Block:
        """Current block, reviving flow into an unreachable block after
        a terminator (dead code still gets parsed, never linked)."""
        if self.cur is None:
            self.cur = self._new()
        return self.cur

    # -- statement dispatch -------------------------------------------------

    def build_stmts(self, stmts: list[ast.stmt]) -> None:
        for s in stmts:
            self.stmt(s)

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.If):
            self._if(s)
        elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            self._loop(s)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            self._with(s)
        elif isinstance(s, ast.Try) or (
            hasattr(ast, "TryStar") and isinstance(s, ast.TryStar)
        ):
            self._try(s)
        elif isinstance(s, ast.Match):
            self._match(s)
        elif isinstance(s, (ast.Return, ast.Raise)):
            cur = self._live()
            cur.units.append(s)  # the value/exc expression still runs
            self._edge(cur, self.exit)
            self.cur = None
        elif isinstance(s, ast.Break):
            if self.loops:
                self._edge(self._live(), self.loops[-1][1])
                self.cur = None
        elif isinstance(s, ast.Continue):
            if self.loops:
                cur = self._live()
                self._edge(cur, self.loops[-1][0], back=True)
                # the loop eventually exits: post-loop code IS on this
                # path's way to the function exit (forward edge, so a
                # back-edge-cutting traversal still sees it)
                self._edge(cur, self.loops[-1][1])
                self.cur = None
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass  # another scope; its body never runs here
        else:
            # Assign/Expr/ClassDef/Assert/... — straight-line units
            self._live().units.append(s)

    # -- compound statements ------------------------------------------------

    def _with(self, s: ast.With | ast.AsyncWith) -> None:
        # the context expressions evaluate BEFORE the region is entered
        # (a lock is not yet held while its name is being resolved)
        cur = self._live()
        for item in s.items:
            cur.units.append(item.context_expr)
        body_entry = self._new()
        self._edge(cur, body_entry)
        self.cur = body_entry
        start = body_entry.idx
        self.build_stmts(s.body)
        # every block registered while the body was being built belongs
        # to the region — nested compounds (branches, loops, inner
        # withs) allocate theirs inside this window, so membership is
        # closed under nesting by construction
        region = frozenset(range(start, len(self.blocks)))
        if self.cur is not None:
            # a body that fell through continues after the with; a body
            # that terminated (return/raise) must leave flow DEAD, or a
            # with-wrapped early exit would read as falling through and
            # the TPM1102/TPM1301 exit bits would miss it
            after = self._new()
            self._edge(self.cur, after)
            self.cur = after
        self.with_regions.append(WithRegion(node=s, blocks=region))

    def _if(self, s: ast.If) -> None:
        cond = self._live()
        cond.units.append(s.test)
        then_entry = self._new()
        self._edge(cond, then_entry)
        self.cur = then_entry
        self.build_stmts(s.body)
        then_end = self.cur
        else_entry = else_end = None
        if s.orelse:
            else_entry = self._new()
            self._edge(cond, else_entry)
            self.cur = else_entry
            self.build_stmts(s.orelse)
            else_end = self.cur
        join = self._new()
        if then_end is not None:
            self._edge(then_end, join)
        if s.orelse:
            if else_end is not None:
                self._edge(else_end, join)
        else:
            self._edge(cond, join)
        self.branches.append(Branch(
            node=s,
            then_entry=then_entry,
            else_entry=else_entry if else_entry is not None else join,
            then_exits=then_end is None,
            else_exits=bool(s.orelse) and else_end is None,
        ))
        self.cur = join

    def _loop(self, s: ast.For | ast.AsyncFor | ast.While) -> None:
        header = self._new()
        self._edge(self._live(), header)
        header.units.append(
            s.test if isinstance(s, ast.While) else s.iter
        )
        # the after-block must NUMBER after the body blocks (reachable()
        # orders events by block idx — an early idx would emit post-loop
        # events before the loop body's), but break targets need the
        # OBJECT now: allocate unregistered, register post-body
        after = Block(-1)
        self.loops.append((header, after))
        body_entry = self._new()
        self._edge(header, body_entry)
        self.cur = body_entry
        self.build_stmts(s.body)
        if self.cur is not None:
            self._edge(self.cur, header, back=True)
            # fall-through also reaches post-loop code on its way to
            # the exit: without this forward edge, a traversal from a
            # branch inside the body could never see the code after
            # the loop (the back edge is cut), missing exactly the
            # early-exit-in-loop deadlock shape
            self._edge(self.cur, after)
        self.loops.pop()
        after.idx = len(self.blocks)
        self.blocks.append(after)
        self._edge(header, after)  # zero-iteration / normal exit
        self.cur = after
        if s.orelse:  # runs on the fallthrough path (approximation)
            self.build_stmts(s.orelse)

    def _try(self, s) -> None:
        pre = self._live()
        self.build_stmts(s.body)
        if self.cur is not None and s.orelse:
            self.build_stmts(s.orelse)
        ends: list[Block] = []
        if self.cur is not None:
            ends.append(self.cur)
        for h in s.handlers:
            hb = self._new()
            # exceptions fork before the try body completes; forking
            # from the pre-try block is the conservative stand-in
            self._edge(pre, hb)
            self.cur = hb
            self.build_stmts(h.body)
            if self.cur is not None:
                ends.append(self.cur)
        join = self._new()
        for e in ends:
            self._edge(e, join)
        self.cur = join if ends else None
        if s.finalbody:
            # fallthrough-path approximation; a terminated try/except
            # still runs finally, so revive flow for it
            self._live()
            self.build_stmts(s.finalbody)

    def _match(self, s: ast.Match) -> None:
        cond = self._live()
        cond.units.append(s.subject)
        ends: list[Block] = []
        for case in s.cases:
            cb = self._new()
            self._edge(cond, cb)
            self.cur = cb
            self.build_stmts(case.body)
            if self.cur is not None:
                ends.append(self.cur)
        join = self._new()
        self._edge(cond, join)  # no case matched
        for e in ends:
            self._edge(e, join)
        self.cur = join


def build(node: ast.AST) -> CFG:
    """CFG over a function def's own body (nested defs excluded)."""
    b = _Builder()
    b.build_stmts(node.body)
    if b.cur is not None:  # implicit return at the end of the body
        b._edge(b.cur, b.exit)
    return CFG(entry=b.entry, exit=b.exit, blocks=b.blocks,
               branches=b.branches, with_regions=b.with_regions)
