"""Collective-protocol verifier (ISSUE 18 tentpole).

Compiles the whole program into a **collective schedule automaton** and
checks it two ways:

* **statically** (:class:`ProtocolIndex`): every function's ``proto``
  event tree (:mod:`tpu_mpi_tests.analysis.program`) is summarized
  bottom-up into a regular schedule — ordered collective/broadcast
  events with ``loop``/``alt``/``try`` structure, calls expanded
  through the project summaries. Rank-feasible path pairs must emit
  matching sequences. Three conviction shapes the per-branch TPM11xx
  rules cannot see:

  - **TPM1701** — rank-divergent whole-program schedule. Two channels:
    broadcast-class events (``fleet.bcast`` → ``_device_bcast`` →
    ``broadcast_one_to_all`` spans three functions and is deliberately
    outside TPM1101's alphabet), and branches on the *return value* of
    a rank-returning function (``mode = pick(); if mode:`` — no
    lexical rank test anywhere near the divergence). Branches whose
    core-collective sequences already differ stay TPM1101/TPM1102
    convictions — each divergent branch carries exactly one code.
  - **TPM1702** — a loop whose trip count derives from a rank-dependent
    value encloses a collective: ranks agree on every op yet execute
    different *counts* of it, the divergent-loop-structure deadlock.
  - **TPM1703** — a ``try`` whose body dispatches collectives has a
    non-exiting handler with a different collective sequence: the rank
    that catches skips its partner op while the rest block in it. A
    handler that re-raises/returns is the sanctioned abort shape.

* **against reality** (:func:`conform_paths`, ``tpumt-lint
  --conform``): the per-function trees are lowered into one NFA over
  runtime ``(op, axis)`` span events. The runtime alphabet is derived
  from the telemetry *emitters themselves* — a ``comm_span("allreduce",
  ...)`` inside the wrapper is the exact op its ``kind:"span"`` record
  carries — so there is no hand-written wrapper→runtime-op table to go
  stale. Dynamically-named spans (``self.op``, f-strings) become
  skippable wildcard edges; method calls that name-resolution cannot
  see (``spec.step(...)``) fall back to class-hierarchy-style
  candidates (every project function with that final name). Replaying
  a real 2-process stream (PR-17 ``seq``-stamped spans, loaded through
  ``diagnose.load_with_lines`` + the ``.p<i>`` rank-set expansion)
  yields:

  - **TPM1704** — a runtime (op, axis) sequence no static path
    generates: a stale model or a dynamic-dispatch blind spot made
    visible, cited with the longest matched prefix and the diverging
    event;
  - **TPM1705** — a rank's stream ends while a sibling emitted the
    statically-expected next collective: the static twin of
    tpumt-doctor's ``missing_rank``, citing the automaton state and
    the expected op.

  Pre-seq streams (no ``seq`` stamps anywhere) degrade to a visible
  NOTE — insufficient stamps are never a conviction.

Stdlib-only by contract, like the rest of the analysis package.
"""

from __future__ import annotations

from pathlib import Path

from tpu_mpi_tests.analysis.core import Finding, ProjectContext
from tpu_mpi_tests.analysis.program import _MAX_DEPTH

#: hard ceilings keeping the summaries/NFA bounded on adversarial input
_MAX_SUMMARY_DEPTH = 4 * _MAX_DEPTH  # tpumt: ignore[TPM701]
_MAX_CHA_CANDIDATES = 12
_MAX_RESOLVE_ALTS = 6


def _flatten(seq: tuple, depth: int = 0, limit: int = 12) -> list[str]:
    """Human-renderable op list for a summary: loops as ``op*``,
    unresolved alternatives as ``(a|b)``."""
    out: list[str] = []
    if depth > 4:
        return ["…"]
    for el in seq:
        if len(out) >= limit:
            out.append("…")
            break
        if el[0] == "ev":
            out.append(el[1])
        elif el[0] == "loop":
            inner = _flatten(el[1], depth + 1, 4)
            out.append("(" + " ".join(inner) + ")*")
        elif el[0] == "alt":
            a = " ".join(_flatten(el[1], depth + 1, 4))
            b = " ".join(_flatten(el[2], depth + 1, 4))
            out.append(f"({a or '—'}|{b or '—'})")
        elif el[0] == "try":
            out.extend(_flatten(el[1], depth + 1, 4))
    return out


def _render(seq: tuple) -> str:
    ops = _flatten(_norm(seq))
    return "[" + (", ".join(ops) if ops else "—") + "]"


def _proj(seq: tuple, core: bool) -> tuple:
    """Normalize a summary: prune event-free structure (an ``alt`` with
    nothing on either side is control flow, not schedule), collapse
    alternatives whose projections agree, and — with ``core=True`` —
    keep only the TPM11xx core-collective alphabet, the guard that
    keeps a divergence already owned by TPM1101/1102 from
    double-convicting as TPM1701."""
    out: list = []
    for el in seq:
        if el[0] == "ev":
            if el[2] or not core:
                out.append(el)
        elif el[0] == "loop":
            sub = _proj(el[1], core)
            if sub:
                out.append(("loop", sub))
        elif el[0] == "alt":
            a, b = _proj(el[1], core), _proj(el[2], core)
            if a == b:
                out.extend(a)
            elif a or b:
                out.append(("alt", a, b))
        elif el[0] == "try":
            a = _proj(el[1], core)
            hs = tuple(_proj(h, core) for h in el[2])
            if all(h == a for h in hs):
                out.extend(a)
            elif a or any(hs):
                out.append(("try", a, hs))
    return tuple(out)


def _core_proj(seq: tuple) -> tuple:
    return _proj(seq, core=True)


def _norm(seq: tuple) -> tuple:
    return _proj(seq, core=False)


def _has_ev(seq: tuple) -> bool:
    for el in seq:
        if el[0] == "ev":
            return True
        if el[0] == "loop" and _has_ev(el[1]):
            return True
        if el[0] == "alt" and (_has_ev(el[1]) or _has_ev(el[2])):
            return True
        if el[0] == "try" and (_has_ev(el[1])
                               or any(_has_ev(h) for h in el[2])):
            return True
    return False


class ProtocolIndex:
    """Whole-program schedule summaries + the TPM1701/1702/1703 checks.

    Each function's ``proto`` tree is summarized exactly once
    (memoized), findings recorded during that first walk — so a callee
    shared by many entry points is judged once, anchored in its own
    file. Branch summaries are composed with their *continuation* (the
    summary of everything after the branch, built right-to-left in one
    linear pass), which is what lets a rank-guarded early ``return``
    before a broadcast diverge even though both arms are locally
    event-free."""

    def __init__(self, proj: ProjectContext):
        self.index = proj.index
        self._path_of: dict[int, str] = {}
        self._fns: list[dict] = []
        for ff in proj.facts:
            for fn in ff["functions"]:
                self._path_of[id(fn)] = ff["path"]
                self._fns.append(fn)
        self._sum_memo: dict[int, tuple | None] = {}
        self._rank_memo: dict[int, bool] = {}
        self._depth = 0
        self.findings: list[tuple] = []

    # -- rank-returning taint ----------------------------------------------

    def rank_returning(self, fn: dict) -> bool:
        """Does this function return the process rank — directly
        (``return jax.process_index()``) or through a returning helper?"""
        key = id(fn)
        if key in self._rank_memo:
            return self._rank_memo[key]
        self._rank_memo[key] = False  # cycle guard
        val = bool(fn.get("rank_ret"))
        if not val:
            mod = self.index._module_of(fn)
            for target in fn.get("return_targets") or []:
                if any(self.rank_returning(g)
                       for g in self.index.resolve_funcs(target, mod)):
                    val = True
                    break
        self._rank_memo[key] = val
        return val

    def _taint_hit(self, taints: list, module: str) -> str | None:
        for canon in taints or []:
            for g in self.index.resolve_funcs(canon, module):
                if self.rank_returning(g):
                    return canon
        return None

    # -- summaries ----------------------------------------------------------

    def fn_summary(self, fn: dict) -> tuple:
        key = id(fn)
        if key in self._sum_memo:
            return self._sum_memo[key] or ()
        self._sum_memo[key] = None  # in-progress: recursion reads ()
        seq = ()
        if self._depth <= _MAX_SUMMARY_DEPTH:
            self._depth += 1
            try:
                seq, _term = self._summ(fn.get("proto") or [], fn)
            finally:
                self._depth -= 1
        self._sum_memo[key] = seq
        return seq

    def _summ(self, nodes: list, fn: dict) -> tuple[tuple, bool]:
        mod = self.index._module_of(fn)
        cur: tuple = ()
        term = False
        for node in reversed(nodes):
            k = node[0]
            if k == "exit":
                cur, term = (), True
            elif k == "span":
                continue  # runtime-only alphabet: the NFA's, not ours
            elif k == "coll":
                _k, op, _canon, _line, core = node
                cur = (("ev", op, core),) + cur
            elif k == "call":
                funcs = self.index.resolve_funcs(node[1], mod)
                if funcs:
                    cur = self.fn_summary(funcs[0]) + cur
            elif k == "loop":
                _k, line, rk, taints, body = node
                bseq, _bt = self._summ(body, fn)
                tcanon = None if rk else self._taint_hit(taints, mod)
                if (rk or tcanon) and _has_ev(bseq):
                    self._emit_1702(fn, line, tcanon, bseq)
                if bseq:
                    cur = (("loop", bseq),) + cur
            elif k == "alt":
                _k, line, col, rk, taints, then, orelse = node
                tseq, tterm = self._summ(then, fn)
                eseq, eterm = self._summ(orelse, fn)
                full_t = tseq if tterm else tseq + cur
                full_e = eseq if eterm else eseq + cur
                ft = tterm or term
                fe = eterm or term
                tcanon = None if rk else self._taint_hit(taints, mod)
                if rk or tcanon:
                    self._check_alt(fn, line, col, rk, tcanon,
                                    full_t, full_e)
                if full_t == full_e and ft == fe:
                    cur, term = full_t, ft
                else:
                    cur, term = (("alt", full_t, full_e),), ft and fe
            elif k == "try":
                _k, line, body, handlers = node
                bseq, _bt = self._summ(body, fn)
                hsums = []
                for h_term, h_nodes in handlers:
                    hseq, hterm2 = self._summ(h_nodes, fn)
                    hsums.append((bool(h_term) or hterm2, hseq))
                self._check_try(fn, line, bseq, hsums)
                hseqs = tuple(h for _t, h in hsums)
                if all(h == bseq for h in hseqs):
                    cur = bseq + cur
                elif bseq or any(hseqs):
                    cur = (("try", bseq, hseqs),) + cur
        return cur, term

    # -- the static convictions --------------------------------------------

    def _emit(self, fn: dict, line: int, col: int, code: str,
              msg: str) -> None:
        self.findings.append(
            (self._path_of.get(id(fn), "?"), line, col, code, msg)
        )

    def _check_alt(self, fn: dict, line: int, col: int, rk: int,
                   tcanon: str | None, full_t: tuple,
                   full_e: tuple) -> None:
        full_t, full_e = _norm(full_t), _norm(full_e)
        if full_t == full_e:
            return
        if rk and _core_proj(full_t) != _core_proj(full_e):
            return  # TPM1101/TPM1102 own the core-alphabet divergence
        via = (
            f"branch tests the return value of {tcanon} (a "
            f"rank-returning function — the taint channel no lexical "
            f"rank test reveals)" if tcanon else
            "divergence is in the broadcast-class events TPM1101's "
            "alphabet deliberately excludes"
        )
        self._emit(
            fn, line, col, "TPM1701",
            f"rank-divergent whole-program schedule: the composed "
            f"schedule is {_render(full_t)} on the guarded path vs "
            f"{_render(full_e)} on the other — {via}; ranks that skip "
            f"a replication/collective point the rest enter hang the "
            f"fleet. Hoist the op out of the rank-dependent region "
            f"(or broadcast the deciding value first)",
        )

    def _emit_1702(self, fn: dict, line: int, tcanon: str | None,
                   bseq: tuple) -> None:
        via = (f"trip count tainted by {tcanon} (rank-returning)"
               if tcanon else "trip count is a function of the rank")
        self._emit(
            fn, line, 0, "TPM1702",
            f"rank-dependent loop bound encloses collective schedule "
            f"{_render(bseq)} — {via}; ranks agree on every op but "
            f"execute different trip counts, so some rank enters an "
            f"iteration its partners never will (the divergent-loop "
            f"deadlock). Derive the bound from a replicated value",
        )

    def _check_try(self, fn: dict, line: int, bseq: tuple,
                   hsums: list[tuple[bool, tuple]]) -> None:
        core_b = _core_proj(bseq)
        for h_term, hseq in hsums:
            if h_term:
                continue  # re-raise/return: the sanctioned abort shape
            core_h = _core_proj(hseq)
            if core_h == core_b or not (core_b or core_h):
                continue
            self._emit(
                fn, line, 0, "TPM1703",
                f"collective schedule {_render(bseq)} is reachable "
                f"under an exception path whose surviving handler "
                f"continues with {_render(hseq)} — the rank that "
                f"catches skips a partner op the other ranks block "
                f"in. Re-raise (or return) from the handler, or move "
                f"the collective out of the try body",
            )
            return  # one conviction per try statement

    # -- driver -------------------------------------------------------------

    def check_all(self) -> list[tuple]:
        for fn in self._fns:
            self.fn_summary(fn)
        self.findings.sort()
        return self.findings


# ---------------------------------------------------------------------------
# the runtime-facing NFA (``--conform`` / the doctor's protocol model)


class ScheduleAutomaton:
    """One NFA over runtime ``(op, axis)`` span events for the whole
    program: every function contributes a shared fragment (call edges
    are ε-jumps into the callee fragment and back — context-insensitive
    returns over-approximate, which only ever makes the model MORE
    permissive, the safe direction for conformance). The union start
    state ε-reaches every function, so any entry point's schedule is in
    the language."""

    def __init__(self, proj: ProjectContext):
        self.index = proj.index
        self._eps: dict[int, set[int]] = {}
        self._edges: dict[int, list[tuple]] = {}
        self._frag: dict[int, tuple[int, int]] = {}
        self._n = 0
        self.modeled_ops: set[str] = set()
        # CHA fallback: final-name → candidate functions (method calls
        # through objects resolve by suffix, conformance-only)
        self._by_last: dict[str, list[dict]] = {}
        fns: list[dict] = []
        for ff in proj.facts:
            for fn in ff["functions"]:
                fns.append(fn)
                last = fn["name"].rsplit(".", 1)[-1]
                self._by_last.setdefault(last, []).append(fn)
        self.start = self._new()
        for fn in fns:
            en, _ex = self._fn_frag(fn)
            self._ep(self.start, en)

    # -- construction -------------------------------------------------------

    def _new(self) -> int:
        self._n += 1
        return self._n

    def _ep(self, a: int, b: int) -> None:
        self._eps.setdefault(a, set()).add(b)

    def _edge(self, a: int, op: str | None, axis: str | None,
              b: int) -> None:
        self._edges.setdefault(a, []).append((op, axis, b))

    def _fn_frag(self, fn: dict) -> tuple[int, int]:
        key = id(fn)
        if key in self._frag:
            return self._frag[key]
        en, ex = self._new(), self._new()
        self._frag[key] = (en, ex)  # pre-registered: recursion closes
        end = self._build(fn.get("proto") or [], en, fn, ex)
        self._ep(end, ex)
        return en, ex

    def _callees(self, canon: str, module: str) -> tuple[list[dict], bool]:
        funcs = self.index.resolve_funcs(canon, module)
        if funcs:
            return funcs[:_MAX_RESOLVE_ALTS], False
        last = canon.rsplit(".", 1)[-1]
        if "." in canon and last:
            cands = self._by_last.get(last, [])
            if 0 < len(cands) <= _MAX_CHA_CANDIDATES:
                return cands, True
        return [], False

    def _build(self, nodes: list, cur: int, fn: dict,
               fn_exit: int) -> int:
        mod = self.index._module_of(fn)
        for node in nodes:
            k = node[0]
            if k == "exit":
                self._ep(cur, fn_exit)
                cur = self._new()  # unreachable continuation
            elif k == "span":
                _k, op, axis, _line = node
                nxt = self._new()
                self._edge(cur, op, axis, nxt)
                if op is None:
                    # dynamically-named span: may also be projected out
                    # of the stream as unmodeled — make it skippable
                    self._ep(cur, nxt)
                else:
                    self.modeled_ops.add(op)
                cur = nxt
            elif k in ("coll", "call"):
                canon = node[2] if k == "coll" else node[1]
                funcs, via_cha = self._callees(canon or "", mod)
                nxt = self._new()
                if not funcs or via_cha:
                    # unresolved (jax-level collectives emit no spans)
                    # or heuristic candidates: never mandatory
                    self._ep(cur, nxt)
                for g in funcs:
                    ge, gx = self._fn_frag(g)
                    self._ep(cur, ge)
                    self._ep(gx, nxt)
                cur = nxt
            elif k == "loop":
                body = node[4]
                en = self._new()
                self._ep(cur, en)
                end = self._build(body, en, fn, fn_exit)
                self._ep(end, en)  # next iteration
                nxt = self._new()
                self._ep(en, nxt)  # zero or n iterations
                cur = nxt
            elif k == "alt":
                then, orelse = node[5], node[6]
                nxt = self._new()
                for branch in (then, orelse):
                    bs = self._new()
                    self._ep(cur, bs)
                    be = self._build(branch, bs, fn, fn_exit)
                    self._ep(be, nxt)
                cur = nxt
            elif k == "try":
                body, handlers = node[2], node[3]
                bs = self._new()
                self._ep(cur, bs)
                be = self._build(body, bs, fn, fn_exit)
                nxt = self._new()
                self._ep(be, nxt)
                for _term, h_nodes in handlers:
                    hs = self._new()
                    self._ep(cur, hs)  # raise before any event
                    self._ep(be, hs)   # raise after the body's events
                    he = self._build(h_nodes, hs, fn, fn_exit)
                    self._ep(he, nxt)
                cur = nxt
        return cur

    # -- simulation ---------------------------------------------------------

    def closure(self, states: set[int]) -> frozenset:
        out = set(states)
        work = list(states)
        while work:
            s = work.pop()
            for t in self._eps.get(s, ()):
                if t not in out:
                    out.add(t)
                    work.append(t)
        return frozenset(out)

    def step(self, states: frozenset, op: str,
             axis: str | None) -> frozenset:
        out: set[int] = set()
        for s in states:
            for eop, eaxis, dst in self._edges.get(s, ()):
                if eop is not None and eop != op:
                    continue
                if eop is not None and eaxis is not None \
                        and axis is not None and eaxis != axis:
                    continue
                out.add(dst)
        return self.closure(out)

    def expected(self, states: frozenset) -> list[str]:
        ops = {eop for s in states
               for eop, _ax, _d in self._edges.get(s, ()) if eop}
        return sorted(ops)


# ---------------------------------------------------------------------------
# conformance replay (``tpumt-lint --conform``)


class _Sim:
    __slots__ = ("rank", "path", "events", "ok", "final", "matched",
                 "last_line")

    def __init__(self, rank, path, events):
        self.rank = rank
        self.path = path
        self.events = events  # [(op, axis, line, seq)]
        self.ok = False
        self.final: frozenset = frozenset()
        self.matched = 0
        self.last_line = events[-1][2] if events else 1


def _stream_events(pairs, auto: ScheduleAutomaton):
    """(rank|None, span records, modeled events) for one file's newest
    run segment."""
    from tpu_mpi_tests.instrument.diagnose import _choose_segment

    seg = _choose_segment(pairs)
    mrank = None
    for _ln, rec in seg:
        if rec.get("kind") == "manifest":
            mrank = rec.get("process_index")
            break
    spans = [(ln, r) for ln, r in seg
             if r.get("kind") == "span" and r.get("op")]
    events = [(r["op"], r.get("axis"), ln, r.get("seq"))
              for ln, r in spans if r["op"] in auto.modeled_ops]
    return mrank, spans, events


def _rank_from_name(path: str) -> int | None:
    stem = Path(path).name
    if ".p" in stem:
        tail = stem.rsplit(".p", 1)[1].split(".")[0]
        if tail.isdigit():
            return int(tail)
    return None


def conform_paths(jsonl_paths, proj: ProjectContext,
                  ) -> tuple[list[Finding], list[str]]:
    """Replay telemetry streams against the schedule automaton.

    Returns ``(findings, notes)``: TPM1704/TPM1705 findings anchored at
    ``<jsonl>:<line>`` plus the human NOTE lines (insufficient stamps,
    unmodeled ops skipped, asymmetries the automaton cannot convict).
    """
    from tpu_mpi_tests.instrument.aggregate import expand_rank_files
    from tpu_mpi_tests.instrument.diagnose import load_with_lines

    auto = ScheduleAutomaton(proj)
    findings: list[Finding] = []
    notes: list[str] = []
    sims: list[_Sim] = []

    files = [str(p) for p in expand_rank_files([str(p)
                                                for p in jsonl_paths])]
    for idx, path in enumerate(files):
        pairs = load_with_lines(path, "tpumt-lint")
        mrank, spans, events = _stream_events(pairs, auto)
        named = _rank_from_name(path) if mrank is None else mrank
        rank = idx if named is None else named
        if not spans:
            notes.append(f"{path}: no span records in the newest run "
                         f"segment — nothing to conform")
            continue
        if not any("seq" in r for _ln, r in spans):
            notes.append(
                f"{path}: insufficient stamps — no span carries the "
                f"per-(op, axis) seq counter (pre-seq telemetry); "
                f"stream skipped, never convicted"
            )
            continue
        skipped = len(spans) - len(events)
        if skipped:
            notes.append(
                f"{path}: {skipped} span(s) with dynamically-named ops "
                f"outside the static model skipped"
            )
        sims.append(_Sim(rank, path, events))

    for sim in sims:
        states = auto.closure({auto.start})
        stuck = None
        for op, axis, ln, seq in sim.events:
            nxt = auto.step(states, op, axis)
            if not nxt:
                stuck = (op, axis, ln, seq)
                break
            states = nxt
            sim.matched += 1
        if stuck is not None:
            op, axis, ln, seq = stuck
            exp = auto.expected(states)
            findings.append(Finding(
                sim.path, ln, 0, "TPM1704",
                f"rank {sim.rank} emitted a collective sequence no "
                f"static path generates: after {sim.matched} matched "
                f"event(s), span op={op!r} axis={axis!r} seq={seq} "
                f"diverges from the schedule automaton (expected next: "
                f"{', '.join(exp[:6]) or 'none'}) — stale model or a "
                f"dynamic-dispatch blind spot; re-lint, or teach the "
                f"protocol layer the new dispatch shape",
            ))
        else:
            sim.ok = True
            sim.final = states

    oks = [s for s in sims if s.ok]
    for a in oks:
        for b in oks:
            if a is b or len(a.events) >= len(b.events):
                continue
            ea = [(op, ax) for op, ax, _ln, _sq in a.events]
            eb = [(op, ax) for op, ax, _ln, _sq in b.events]
            if eb[:len(ea)] != ea:
                i = next(j for j in range(len(ea))
                         if ea[j] != eb[j])
                notes.append(
                    f"{a.path}: rank {a.rank} and rank {b.rank} "
                    f"diverge mid-stream at event {i} "
                    f"({ea[i][0]} vs {eb[i][0]}) with both streams "
                    f"individually generable — runtime asymmetry is "
                    f"tpumt-doctor's domain, not a static conviction"
                )
                continue
            op, ax = eb[len(ea)]
            bln = b.events[len(ea)][2]
            exp = auto.expected(a.final)
            if op in exp:
                exp = [op] + [e for e in exp if e != op]
            if auto.step(a.final, op, ax):
                findings.append(Finding(
                    a.path, a.last_line, 0, "TPM1705",
                    f"rank {a.rank} stream ends after "
                    f"{len(ea)} event(s) with a statically mandatory "
                    f"collective un-emitted: sibling rank {b.rank} "
                    f"emitted op={op!r} axis={ax!r} next "
                    f"({b.path}:{bln}), and the automaton expects it "
                    f"from rank {a.rank}'s state "
                    f"({len(a.final)} state(s); expected next: "
                    f"{', '.join(exp[:6])}) — the "
                    f"static twin of tpumt-doctor's missing_rank",
                ))
                break  # one conviction per short rank
            notes.append(
                f"{a.path}: rank {a.rank} stopped {len(eb) - len(ea)} "
                f"event(s) short of rank {b.rank}, but the automaton "
                f"cannot place {op!r} from its state — no conviction"
            )
    findings.sort()
    return findings, notes


# ---------------------------------------------------------------------------
# doctor evidence (``tpumt-doctor --protocol-model``)


def facts_from_cache(cache_path: str) -> list[dict] | None:
    """Facts replayed from a WARM lint cache, no parsing: every cache
    entry whose digest still matches the file on disk contributes. None
    when the cache is cold/absent — the doctor's protocol evidence is
    strictly optional and must never trigger an analysis run."""
    import hashlib

    from tpu_mpi_tests.analysis.core import replay_cache_entry
    from tpu_mpi_tests.analysis.lintcache import LintCache

    try:
        cache = LintCache(cache_path)
    except Exception:
        return None
    facts: list[dict] = []
    for path, entry in cache._entries.items():
        p = Path(path)
        try:
            digest = hashlib.sha256(p.read_bytes()).hexdigest()
        except OSError:
            continue
        if entry.get("hash") != digest:
            continue
        replay = replay_cache_entry(entry, path)
        if replay is None:
            continue
        facts.append(replay[1])
    return facts or None


def automaton_from_cache(cache_path: str) -> ScheduleAutomaton | None:
    """The whole-program schedule automaton rebuilt from a warm lint
    cache, or None when the cache replays nothing — built once per
    doctor run and shared across that run's findings."""
    facts = facts_from_cache(cache_path)
    if not facts:
        return None
    return ScheduleAutomaton(ProjectContext(facts, {}))


def expected_after(records: list[tuple[int, dict]],
                   auto: ScheduleAutomaton,
                   siblings: list[list[tuple[int, dict]]] = (),
                   ) -> dict | None:
    """For a dead/stalled rank's record stream: the statically-expected
    next collective under ``auto``. Returns ``{"expected": [...],
    "matched": n, "states": k}`` or None when the stream is pre-seq,
    has no spans, or already left the model — no conviction here, the
    doctor only cites evidence. ``siblings`` are other ranks' record
    streams: when one of them emitted an op at the position this stream
    died at, that op is fronted in the expected list before the
    alphabetical cap (the same sibling-witness ordering TPM1705 uses —
    the wildcard-widened automaton can expect far more than six ops,
    and the one a live sibling actually ran next is the one worth
    reading first)."""
    _mrank, spans, events = _stream_events(records, auto)
    if not spans or not any("seq" in r for _ln, r in spans):
        return None
    states = auto.closure({auto.start})
    matched = 0
    for op, axis, _ln, _seq in events:
        nxt = auto.step(states, op, axis)
        if not nxt:
            return None
        states = nxt
        matched += 1
    exp = auto.expected(states)
    if not exp:
        return None
    for sib in siblings:
        _r, _s, sev = _stream_events(sib, auto)
        if matched < len(sev) and sev[matched][0] in exp:
            op = sev[matched][0]
            exp = [op] + [e for e in exp if e != op]
            break
    return {"expected": exp[:6], "matched": matched,
            "states": len(states)}
