"""``tpumt-lint`` engine: file walking, rule registry, suppressions.

The engine is deliberately small: it parses each file once (``ast``),
hands the tree to every registered file-scope rule, extracts the file's
serializable *facts* (module imports, function summaries, axis
bindings — :mod:`tpu_mpi_tests.analysis.program`), hands the whole fact
set to project-scope rules (import reachability, collective divergence,
donation safety all need the cross-file view), then applies
``# tpumt: ignore[TPMxxx]`` suppression comments and reports any
suppression that silenced nothing (an unused suppression is itself a
finding — stale ignores are how gated bug classes sneak back in).

Incrementality (ISSUE 10): file-scope findings and facts depend only on
the file's bytes, so both are cached under a content hash
(:mod:`tpu_mpi_tests.analysis.lintcache`) — an unchanged file skips
parse + rules + summary extraction entirely, and the project pass runs
over deserialized summaries. Project findings are recomputed every run
(they depend on the whole file set) but that pass is cheap by design.

Stdlib-only by contract (verified by ``tests/test_entry_points.py``):
the linter must run on login nodes where ``import jax`` raises.
"""

from __future__ import annotations

import ast
import hashlib
import io
import re
import time
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator

#: console-script entry points whose import closure must stay jax-free
#: (the TPM4xx reachability roots; tests may substitute their own set)
DEFAULT_ENTRY_MODULES = {
    "tpu_mpi_tests.instrument.aggregate": "tpumt-report",
    "tpu_mpi_tests.instrument.timeline": "tpumt-trace",
    "tpu_mpi_tests.instrument.diagnose": "tpumt-doctor",
    "tpu_mpi_tests.instrument.live": "tpumt-top",
    "tpu_mpi_tests.analysis.cli": "tpumt-lint",
    "tpu_mpi_tests.analysis.records": "tpumt-records",
    "tpu_mpi_tests.tune.pack": "tpumt-tune",
    # the rule modules load lazily at lint time (all_rules()), which the
    # static reachability walk cannot see — root them explicitly so an
    # eager jax import in a rule module is still caught
    "tpu_mpi_tests.analysis.rules": "tpumt-lint",
}

#: directory names never descended into on a recursive walk. ``fixtures``
#: keeps the rule golden files (deliberately-bad code under
#: ``analysis/fixtures/``) out of the self-clean gate; explicit file
#: arguments are always linted, which is how the golden tests reach them.
SKIP_DIRS = {"__pycache__", "fixtures", "node_modules"}

def is_test_file(path) -> bool:
    """Test modules are exempt from the contract-style rules (record
    contract, chaos containment): tests assert on the artifacts, they
    are not contract parties. Accepts a path OR a bare module
    component (``test_foo.py`` and ``test_foo`` both match)."""
    name = Path(str(path)).name
    stem = name[:-3] if name.endswith(".py") else name
    return stem.startswith("test_") or stem == "conftest"


_ENGINE_CODES = {
    "TPM900": "unused suppression: the silenced finding is gone",
    "TPM901": "malformed `# tpumt:` comment",
    "TPM902": "file cannot be read or parsed",
}


@dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    col: int
    code: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} " \
               f"{self.message}"

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "code": self.code, "message": self.message}


def attr_parts(node: ast.AST) -> list[str] | None:
    """``a.b.c`` → ``["a", "b", "c"]``; None when the chain's root is not
    a plain name (e.g. ``f(x).block_until_ready``)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def last_attr(node: ast.AST) -> str | None:
    """Final component of a call target (method/function name)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


# ---------------------------------------------------------------------------
# shared AST heuristics (previously rules/_util.py, hoisted so the
# whole-program facts extractor can use them without importing the rule
# registry — rules/_util re-exports them for the rule modules)

#: call targets that put a function under a jax trace — the bodies they
#: receive run ONCE at trace time, not per execution
TRACE_ENTRIES = {"jit", "shard_map", "pallas_call"}

#: origin-module prefixes whose calls dispatch device work in this repo
DEVICE_ORIGINS = ("jax", "tpu_mpi_tests.kernels", "tpu_mpi_tests.comm")

#: origins whose return values are device-dispatching callables (the
#: compiled-fn factories: halo iterate builders, pick_kernel_tier, ...)
FACTORY_ORIGINS = DEVICE_ORIGINS + ("tpu_mpi_tests.drivers",)

#: compiled-fn factories convicted BY NAME, independent of whether the
#: import graph resolved their origin (aliased/dynamic imports):
#: ``pick_kernel_tier``'s step and the ISSUE-15 fused-tier runner — a
#: perf_counter pair timing either's result without a sync is a TPM1xx
#: finding (fixture ``tpm1_factory_bad.py``)
FACTORY_NAMES = {"pick_kernel_tier", "iterate_fused_rdma_fn"}


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def has_trace_entry(node: ast.AST) -> bool:
    """True when the expression mentions jit/shard_map/pallas_call —
    used on decorators (``@functools.partial(jax.jit, ...)`` included)
    and on call targets (``jax.jit(f)``)."""
    for n in ast.walk(node):
        name = None
        if isinstance(n, ast.Attribute):
            name = n.attr
        elif isinstance(n, ast.Name):
            name = n.id
        if name in TRACE_ENTRIES:
            return True
    return False


def traced_functions(ctx: "FileContext") -> list[ast.AST]:
    """Function nodes (defs and lambdas) whose body runs under a jax
    trace: jit/shard_map/pallas_call decorators, or being passed as the
    first argument to such a call (``shard_map(body, mesh=...)``,
    ``pl.pallas_call(kernel, ...)``, ``jax.jit(f)``)."""
    defs_by_name: dict[str, list[ast.AST]] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(n.name, []).append(n)

    traced: list[ast.AST] = []
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(has_trace_entry(d) for d in n.decorator_list):
                traced.append(n)
        elif isinstance(n, ast.Call) and has_trace_entry(n.func) and n.args:
            first = n.args[0]
            if isinstance(first, ast.Lambda):
                traced.append(first)
            elif isinstance(first, ast.Name):
                traced.extend(defs_by_name.get(first.id, ()))
    return traced


def device_callables(ctx: "FileContext") -> set[str]:
    """Local names that dispatch device work when called: functions with
    a trace-entry decorator, or names assigned from a call into jax /
    the comm / kernels layers (compiled-fn factories)."""
    out: set[str] = set()
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(has_trace_entry(d) for d in n.decorator_list):
                out.add(n.name)
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            resolved = ctx.imports.resolve(n.value.func) or ""
            if not (resolved.startswith(FACTORY_ORIGINS)
                    or last_attr(n.value.func) in FACTORY_NAMES
                    or has_trace_entry(n.value.func)):
                continue
            for t in n.targets:
                targets = t.elts if isinstance(
                    t, (ast.Tuple, ast.List)
                ) else [t]
                out.update(e.id for e in targets
                           if isinstance(e, ast.Name))
    return out


def is_device_call(ctx: "FileContext", call: ast.Call,
                   local_device: set[str]) -> bool:
    """Does this call plausibly dispatch (async) device work?"""
    parts = attr_parts(call.func)
    if not parts:
        return False
    if parts[0] in local_device and len(parts) == 1:
        return True
    origin = ctx.imports.origin(parts[0])
    return bool(origin and origin.startswith(DEVICE_ORIGINS))


def own_nodes(root: ast.AST) -> Iterator[ast.AST]:
    """In-order walk of ``root``'s subtree, skipping nested function and
    lambda bodies — "own scope": what executes when this code object
    runs, not what it merely defines. Shared by the facts extractor
    (program.py) and the lockset layer (locks.py) — one definition, so
    their scope semantics cannot diverge."""
    for child in ast.iter_child_nodes(root):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from own_nodes(child)


def stmt_lists(tree: ast.AST) -> Iterator[list[ast.stmt]]:
    """Every statement list in the tree (module/function/branch bodies)."""
    for n in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(n, field, None)
            if isinstance(stmts, list) and stmts and isinstance(
                stmts[0], ast.stmt
            ):
                yield stmts


def call_name(node: ast.AST) -> str:
    return last_attr(node) or "<call>"


# ---------------------------------------------------------------------------


class ImportMap:
    """Local-name → origin-module resolution for one file.

    Imports are collected from the WHOLE tree (drivers import jax inside
    ``run()`` by convention, and those bindings are what the rule
    heuristics need to resolve)."""

    def __init__(self) -> None:
        self.modules: dict[str, str] = {}  # alias -> dotted module
        self.names: dict[str, tuple[str, str]] = {}  # name -> (mod, orig)

    @classmethod
    def collect(cls, tree: ast.AST) -> "ImportMap":
        m = cls()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        m.modules[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        m.modules.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    m.names[a.asname or a.name] = (mod, a.name)
        return m

    def origin(self, root: str) -> str | None:
        """Dotted origin of a local name: the module it aliases, or
        ``module.original`` for a from-import; None if unknown."""
        if root in self.modules:
            return self.modules[root]
        if root in self.names:
            mod, orig = self.names[root]
            return f"{mod}.{orig}" if mod else orig
        return None

    def resolve(self, func: ast.AST) -> str | None:
        """Canonical dotted name of a call target with the root alias
        substituted by its import origin (``jnp.asarray`` →
        ``jax.numpy.asarray``). None for non-name roots."""
        parts = attr_parts(func)
        if not parts:
            return None
        origin = self.origin(parts[0])
        if origin:
            return ".".join([origin] + parts[1:])
        return ".".join(parts)


def module_name(path: str) -> str:
    """Importable dotted name of a file, anchored at the topmost enclosing
    directory that still has an ``__init__.py`` (so fixture mini-packages
    resolve relative to themselves, not the repo)."""
    p = Path(path).resolve()
    parts = [] if p.name == "__init__.py" else [p.stem]
    d = p.parent
    while (d / "__init__.py").exists():
        parts.insert(0, d.name)
        d = d.parent
    return ".".join(parts)


class FileContext:
    """One parsed file plus the lookups every rule shares."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.module = module_name(path)
        self.imports = ImportMap.collect(tree)


class ProjectContext:
    """The full linted file set as serializable per-file *facts*
    (:func:`tpu_mpi_tests.analysis.program.extract_facts`) — project
    rules consume facts, never trees, so a warm-cache run hands them the
    identical view without re-parsing anything. Module names map to
    LISTS of facts: two linted roots can legitimately contain same-named
    modules (e.g. fixture mini-trees), and collapsing them to one would
    silently drop files from the reachability scan."""

    def __init__(self, facts: list[dict],
                 entry_modules: dict[str, str]):
        self.facts = facts
        self.entry_modules = entry_modules
        self.by_module: dict[str, list[dict]] = {}
        for ff in facts:
            if ff["module"]:
                self.by_module.setdefault(ff["module"], []).append(ff)
        self._index = None

    @property
    def index(self):
        """Lazily-built whole-program symbol table / call graph
        (:class:`tpu_mpi_tests.analysis.program.ProjectIndex`)."""
        if self._index is None:
            from tpu_mpi_tests.analysis.program import ProjectIndex

            self._index = ProjectIndex(self.facts)
        return self._index


_SUPPRESS_RE = re.compile(r"tpumt:\s*ignore\[([A-Za-z0-9_,\s]*)\]")


@dataclass
class Suppression:
    """One ``# tpumt: ignore[...]`` comment: the codes it silences, the
    physical lines it applies to (the comment's own line plus the first
    line of its logical statement — findings anchor to a multi-line
    call's first line, while the trailing comment often sits on the
    closing paren), and whether any finding consumed it."""

    codes: set[str]
    lines: set[int]
    comment_line: int
    used_codes: set[str] | None = None

    def __post_init__(self):
        if self.used_codes is None:
            self.used_codes = set()

    def as_dict(self) -> dict:
        return {"codes": sorted(self.codes), "lines": sorted(self.lines),
                "comment_line": self.comment_line}

    @classmethod
    def from_dict(cls, d: dict) -> "Suppression":
        return cls(set(d["codes"]), set(d["lines"]), d["comment_line"])


def collect_suppressions(
    source: str,
) -> tuple[list[Suppression], list[int]]:
    """``# tpumt: ignore[TPM101,TPM201]`` comments plus the lines of
    malformed ``# tpumt:`` comments. Tokenized, not regexed over raw
    lines, so string literals containing the marker (e.g. this linter's
    own tests) cannot false-match."""
    supps: list[Suppression] = []
    malformed: list[int] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return supps, malformed
    _SKIP = (tokenize.NL, tokenize.NEWLINE, tokenize.COMMENT,
             tokenize.INDENT, tokenize.DEDENT, tokenize.ENCODING)
    logical_start: int | None = None
    for tok in tokens:
        if logical_start is None and tok.type not in _SKIP:
            logical_start = tok.start[0]
        if tok.type == tokenize.NEWLINE:
            logical_start = None
        if tok.type != tokenize.COMMENT or "tpumt:" not in tok.string:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        codes = {c.strip().upper() for c in m.group(1).split(",")
                 if c.strip()} if m else set()
        if codes:
            lines = {tok.start[0]}
            if logical_start is not None:
                lines.add(logical_start)
            supps.append(Suppression(codes, lines, tok.start[0]))
        else:
            malformed.append(tok.start[0])
    return supps, malformed


class CodeFilter:
    """``--select``/``--ignore`` semantics: comma lists of codes or
    family prefixes (``TPM1``, ``TPM1xx``, ``TPM101`` all work)."""

    def __init__(self, select: Iterable[str] | None = None,
                 ignore: Iterable[str] | None = None):
        self.select = self._norm(select)
        self.ignore = self._norm(ignore)

    @staticmethod
    def _norm(values: Iterable[str] | None) -> list[str]:
        out: list[str] = []
        for v in values or ():
            for piece in v.split(","):
                piece = piece.strip().upper()
                if piece.endswith("XX"):
                    piece = piece[:-2]
                if piece:
                    out.append(piece)
        return out

    def selected(self, code: str) -> bool:
        if self.select and not any(code.startswith(p) for p in self.select):
            return False
        return not any(code.startswith(p) for p in self.ignore)


def replay_cache_entry(
    entry: dict, path: str,
) -> tuple[list[Finding], dict, list[Suppression], list[int]] | None:
    """Rebuild a cached file's analysis, or None — read as a miss — on
    ANY shape mismatch (a hand-edited/corrupted entry must degrade to a
    cold parse, never crash the run) or when the filesystem-derived
    module name changed out from under the cached facts: an added or
    removed ``__init__.py`` re-anchors :func:`module_name` without
    touching the file's bytes, and replaying facts under the stale name
    would make warm project findings diverge from a cold run."""
    try:
        facts = entry["facts"]
        if facts["module"] != module_name(path):
            return None
        findings = [
            Finding(d["path"], int(d["line"]), int(d["col"]),
                    d["code"], d["message"])
            for d in entry["findings"]
        ]
        supps = [Suppression.from_dict(s) for s in entry["supps"]]
        malformed = [int(x) for x in entry["malformed"]]
    except (TypeError, KeyError, ValueError, AttributeError):
        return None
    return findings, facts, supps, malformed


def all_rules() -> list:
    """The registered rule instances (imported lazily so ``--help`` and
    suppression parsing never load the rule modules)."""
    from tpu_mpi_tests.analysis.rules import ALL_RULES

    return ALL_RULES


def rule_table() -> list[tuple[str, str]]:
    """``(code, summary)`` rows for every registered code, engine codes
    included — the ``--list-rules`` and README source of truth."""
    rows: list[tuple[str, str]] = []
    for rule in all_rules():
        rows.extend(sorted(rule.codes.items()))
    rows.extend(sorted(_ENGINE_CODES.items()))
    return rows


def analyze_file(path: str, source: str | None = None,
                 digest: str | None = None) -> dict:
    """One file's full file-scope analysis as a serializable dict —
    the unit of work ``--jobs`` farms out to worker processes (and the
    sequential path runs inline, passing the ``source``/``digest`` the
    cache lookup already paid for). Shape:

    ``{"path", "digest", "entry": {findings, facts, supps, malformed}}``
    on success, or ``{"path", "error": [line, message]}`` when the file
    cannot be read or parsed (the caller turns that into TPM902)."""
    from tpu_mpi_tests.analysis.program import extract_facts

    if source is None:
        try:
            source = Path(path).read_text()
        except OSError as e:
            return {"path": path, "unreadable": True,
                    "error": [1, f"cannot parse: {e}"]}
    if digest is None:
        digest = hashlib.sha256(source.encode()).hexdigest()
    try:
        tree = ast.parse(source, filename=path)
    except (SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", None) or 1
        return {"path": path, "error": [line, f"cannot parse: {e}"]}
    ctx = FileContext(path, source, tree)
    findings: list[dict] = []
    for rule in all_rules():
        if rule.scope != "file":
            continue
        for line, col, code, msg in rule.check(ctx):
            findings.append(Finding(path, line, col, code,
                                    msg).as_dict())
    facts = extract_facts(ctx)
    supps, malformed = collect_suppressions(source)
    return {
        "path": path, "digest": digest,
        "entry": {
            "findings": findings,
            "facts": facts,
            "supps": [s.as_dict() for s in supps],
            "malformed": malformed,
        },
    }


def iter_files(paths: Iterable[str]) -> Iterator[Path]:
    seen: set[Path] = set()
    for p in paths:
        path = Path(p)
        if path.is_dir():
            for f in sorted(path.rglob("*.py")):
                rel = f.relative_to(path)
                if any(part in SKIP_DIRS or part.startswith(".")
                       for part in rel.parts[:-1]):
                    continue
                if f not in seen:
                    seen.add(f)
                    yield f
        elif path.is_file() and path.suffix == ".py":
            if path not in seen:
                seen.add(path)
                yield path


def _run_pool(miss_paths: list[str], jobs: int) -> list[dict]:
    """``analyze_file`` over a worker pool; degrades to sequential when
    the platform cannot fork/spawn (the lint must never fail because
    its parallelism did)."""
    try:
        import multiprocessing

        with multiprocessing.Pool(jobs) as pool:
            return pool.map(analyze_file, miss_paths)
    except (ImportError, OSError, RuntimeError):
        # RuntimeError: the spawn start method inside an unguarded
        # __main__ (Windows/macOS library callers) refuses to
        # bootstrap — degrade to sequential, never fail the lint
        return [analyze_file(p) for p in miss_paths]


def _gather(
    paths: Iterable[str], cache, jobs: int,
) -> tuple[set, list[dict],
           dict[str, tuple[list[Suppression], list[int]]], int, int, int]:
    """The per-file phase shared by :func:`lint_paths` and
    :func:`collect_project`: cache lookup, (possibly pooled) analysis
    of the misses, cache write-back. Returns ``(raw_findings,
    facts_list, suppressions, n_files, n_analyzed, n_hits)`` — the
    caller decides whether to run rules over the facts or hand them
    straight to the protocol layer."""
    raw: set[Finding] = set()
    facts_list: list[dict] = []
    suppressions: dict[str, tuple[list[Suppression], list[int]]] = {}
    n_files = n_analyzed = n_hits = 0

    # a missing or non-.py path is a broken gate, never a clean one: a
    # renamed directory in the `make lint` path list must fail loudly,
    # not lint nothing and exit 0
    for p in paths:
        path = Path(p)
        if not path.exists():
            raw.add(Finding(str(p), 1, 0, "TPM902",
                            "path does not exist — a lint gate over a "
                            "missing path would pass vacuously"))
        elif path.is_file() and path.suffix != ".py":
            raw.add(Finding(str(p), 1, 0, "TPM902",
                            "not a python file"))

    misses: list[str] = []
    # cache-miss sources the lookup already read, reused by the
    # sequential path (pool workers re-read — sending sources over the
    # pipe would cost more than the read)
    miss_src: dict[str, tuple[str, str]] = {}
    for f in iter_files(paths):
        path = str(f)
        n_files += 1
        if cache is not None:
            try:
                source = f.read_text()
            except OSError as e:
                n_files -= 1
                raw.add(Finding(path, 1, 0, "TPM902",
                                f"cannot parse: {e}"))
                continue
            digest = hashlib.sha256(source.encode()).hexdigest()
            entry = cache.get(path, digest)
            if entry is not None:
                replay = replay_cache_entry(entry, path)
                if replay is not None:
                    n_hits += 1
                    cached_findings, facts, supps, malformed = replay
                    raw.update(cached_findings)
                    facts_list.append(facts)
                    suppressions[path] = (supps, malformed)
                    continue
            miss_src[path] = (source, digest)
        misses.append(path)

    if jobs > 1 and len(misses) > 1:
        results = _run_pool(misses, jobs)
    else:
        results = [analyze_file(p, *miss_src.get(p, (None, None)))
                   for p in misses]

    for res in results:
        path = res["path"]
        if "error" in res:
            if res.get("unreadable"):
                # match the cached path (and the pre-jobs engine):
                # unreadable files never count toward `files`
                n_files -= 1
            line, msg = res["error"]
            raw.add(Finding(path, int(line), 0, "TPM902", msg))
            continue
        n_analyzed += 1
        entry = res["entry"]
        raw.update(
            Finding(d["path"], int(d["line"]), int(d["col"]),
                    d["code"], d["message"])
            for d in entry["findings"]
        )
        facts_list.append(entry["facts"])
        supps = [Suppression.from_dict(s) for s in entry["supps"]]
        suppressions[path] = (supps, list(entry["malformed"]))
        if cache is not None:
            cache.put(path, res["digest"], entry)

    return raw, facts_list, suppressions, n_files, n_analyzed, n_hits


def lint_paths(
    paths: Iterable[str],
    select: Iterable[str] | None = None,
    ignore: Iterable[str] | None = None,
    entry_modules: dict[str, str] | None = None,
    cache_path: str | None = None,
    stats: dict | None = None,
    jobs: int = 1,
) -> list[Finding]:
    """Lint files/directories; returns sorted, suppression-filtered
    findings (unused/malformed suppressions included as findings).

    ``cache_path`` enables the content-hash analysis cache
    (:mod:`tpu_mpi_tests.analysis.lintcache`): unchanged files replay
    their cached file-scope findings + facts instead of re-parsing. The
    default (None) is uncached — library callers and tests stay
    hermetic; the CLI opts in.

    ``jobs`` parallelizes per-file analysis (parse + file rules + fact
    extraction) over a ``multiprocessing`` pool — the facts were made
    JSON-serializable for the cache, which is exactly what lets them
    cross a process boundary. Cache hits are resolved in the parent
    BEFORE dispatch, so a warm run re-parses zero files regardless of
    ``jobs``; the project pass always runs in the parent.

    ``stats``, when a dict, receives ``files``/``analyzed``/
    ``cache_hits``/``seconds``/``jobs`` counts."""
    t0 = time.monotonic()
    code_filter = CodeFilter(select, ignore)

    cache = None
    if cache_path:
        from tpu_mpi_tests.analysis.lintcache import LintCache

        cache = LintCache(cache_path)

    (raw, facts_list, suppressions,
     n_files, n_analyzed, n_hits) = _gather(paths, cache, jobs)

    proj = ProjectContext(facts_list, entry_modules or DEFAULT_ENTRY_MODULES)
    for rule in all_rules():
        if rule.scope != "project":
            continue
        for path, line, col, code, msg in rule.check_project(proj):
            raw.add(Finding(path, line, col, code, msg))

    findings: list[Finding] = []
    for fd in raw:
        if not code_filter.selected(fd.code):
            continue
        matched = False
        for supp in suppressions.get(fd.path, ((), ()))[0]:
            if fd.line in supp.lines and fd.code in supp.codes:
                supp.used_codes.add(fd.code)
                matched = True
        if not matched:
            findings.append(fd)

    for path, (supps, malformed) in suppressions.items():
        for supp in supps:
            for code in sorted(supp.codes - supp.used_codes):
                if not (code_filter.selected(code)
                        and code_filter.selected("TPM900")):
                    continue
                findings.append(Finding(
                    path, supp.comment_line, 0, "TPM900",
                    f"unused suppression for {code} — the finding it "
                    f"silenced is gone; remove the comment",
                ))
        for line in malformed:
            if code_filter.selected("TPM901"):
                findings.append(Finding(
                    path, line, 0, "TPM901",
                    "malformed tpumt comment — expected "
                    "`# tpumt: ignore[TPM101]` (comma-list of codes)",
                ))

    if cache is not None:
        cache.save()
    if stats is not None:
        stats.update(files=n_files, analyzed=n_analyzed,
                     cache_hits=n_hits,
                     seconds=round(time.monotonic() - t0, 3),
                     jobs=jobs)
    findings.sort()
    return findings


def collect_project(
    paths: Iterable[str],
    entry_modules: dict[str, str] | None = None,
    cache_path: str | None = None,
    stats: dict | None = None,
    jobs: int = 1,
) -> ProjectContext:
    """The whole-program facts view WITHOUT running any rules — the
    ``--conform`` entry point. Shares :func:`_gather` with
    :func:`lint_paths`, so a warm cache replays every file's facts
    (``analyzed == 0`` in ``stats``) and the conformance pass rebuilds
    its schedule automata without re-parsing a single file."""
    t0 = time.monotonic()
    cache = None
    if cache_path:
        from tpu_mpi_tests.analysis.lintcache import LintCache

        cache = LintCache(cache_path)
    (_raw, facts_list, _supps,
     n_files, n_analyzed, n_hits) = _gather(paths, cache, jobs)
    if cache is not None:
        cache.save()
    if stats is not None:
        stats.update(files=n_files, analyzed=n_analyzed,
                     cache_hits=n_hits,
                     seconds=round(time.monotonic() - t0, 3),
                     jobs=jobs)
    return ProjectContext(facts_list,
                          entry_modules or DEFAULT_ENTRY_MODULES)
