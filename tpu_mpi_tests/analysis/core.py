"""``tpumt-lint`` engine: file walking, rule registry, suppressions.

The engine is deliberately small: it parses each file once (``ast``),
hands the tree to every registered file-scope rule, hands the whole file
set to project-scope rules (import-reachability needs the graph), then
applies ``# tpumt: ignore[TPMxxx]`` suppression comments and reports any
suppression that silenced nothing (an unused suppression is itself a
finding — stale ignores are how gated bug classes sneak back in).

Stdlib-only by contract (verified by ``tests/test_entry_points.py``):
the linter must run on login nodes where ``import jax`` raises.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator

#: console-script entry points whose import closure must stay jax-free
#: (the TPM4xx reachability roots; tests may substitute their own set)
DEFAULT_ENTRY_MODULES = {
    "tpu_mpi_tests.instrument.aggregate": "tpumt-report",
    "tpu_mpi_tests.instrument.timeline": "tpumt-trace",
    "tpu_mpi_tests.instrument.diagnose": "tpumt-doctor",
    "tpu_mpi_tests.analysis.cli": "tpumt-lint",
    # the rule modules load lazily at lint time (all_rules()), which the
    # static reachability walk cannot see — root them explicitly so an
    # eager jax import in a rule module is still caught
    "tpu_mpi_tests.analysis.rules": "tpumt-lint",
}

#: directory names never descended into on a recursive walk. ``fixtures``
#: keeps the rule golden files (deliberately-bad code under
#: ``analysis/fixtures/``) out of the self-clean gate; explicit file
#: arguments are always linted, which is how the golden tests reach them.
SKIP_DIRS = {"__pycache__", "fixtures", "node_modules"}

_ENGINE_CODES = {
    "TPM900": "unused suppression: the silenced finding is gone",
    "TPM901": "malformed `# tpumt:` comment",
    "TPM902": "file cannot be read or parsed",
}


@dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    col: int
    code: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} " \
               f"{self.message}"

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "code": self.code, "message": self.message}


def attr_parts(node: ast.AST) -> list[str] | None:
    """``a.b.c`` → ``["a", "b", "c"]``; None when the chain's root is not
    a plain name (e.g. ``f(x).block_until_ready``)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def last_attr(node: ast.AST) -> str | None:
    """Final component of a call target (method/function name)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class ImportMap:
    """Local-name → origin-module resolution for one file.

    Imports are collected from the WHOLE tree (drivers import jax inside
    ``run()`` by convention, and those bindings are what the rule
    heuristics need to resolve)."""

    def __init__(self) -> None:
        self.modules: dict[str, str] = {}  # alias -> dotted module
        self.names: dict[str, tuple[str, str]] = {}  # name -> (mod, orig)

    @classmethod
    def collect(cls, tree: ast.AST) -> "ImportMap":
        m = cls()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        m.modules[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        m.modules.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    m.names[a.asname or a.name] = (mod, a.name)
        return m

    def origin(self, root: str) -> str | None:
        """Dotted origin of a local name: the module it aliases, or
        ``module.original`` for a from-import; None if unknown."""
        if root in self.modules:
            return self.modules[root]
        if root in self.names:
            mod, orig = self.names[root]
            return f"{mod}.{orig}" if mod else orig
        return None

    def resolve(self, func: ast.AST) -> str | None:
        """Canonical dotted name of a call target with the root alias
        substituted by its import origin (``jnp.asarray`` →
        ``jax.numpy.asarray``). None for non-name roots."""
        parts = attr_parts(func)
        if not parts:
            return None
        origin = self.origin(parts[0])
        if origin:
            return ".".join([origin] + parts[1:])
        return ".".join(parts)


def module_name(path: str) -> str:
    """Importable dotted name of a file, anchored at the topmost enclosing
    directory that still has an ``__init__.py`` (so fixture mini-packages
    resolve relative to themselves, not the repo)."""
    p = Path(path).resolve()
    parts = [] if p.name == "__init__.py" else [p.stem]
    d = p.parent
    while (d / "__init__.py").exists():
        parts.insert(0, d.name)
        d = d.parent
    return ".".join(parts)


class FileContext:
    """One parsed file plus the lookups every rule shares."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.module = module_name(path)
        self.imports = ImportMap.collect(tree)


class ProjectContext:
    """The full linted file set, for cross-file rules. Module names map
    to LISTS of contexts: two linted roots can legitimately contain
    same-named modules (e.g. fixture mini-trees), and collapsing them
    to one would silently drop files from the reachability scan."""

    def __init__(self, contexts: list[FileContext],
                 entry_modules: dict[str, str]):
        self.contexts = contexts
        self.entry_modules = entry_modules
        self.by_module: dict[str, list[FileContext]] = {}
        for c in contexts:
            if c.module:
                self.by_module.setdefault(c.module, []).append(c)


_SUPPRESS_RE = re.compile(r"tpumt:\s*ignore\[([A-Za-z0-9_,\s]*)\]")


@dataclass
class Suppression:
    """One ``# tpumt: ignore[...]`` comment: the codes it silences, the
    physical lines it applies to (the comment's own line plus the first
    line of its logical statement — findings anchor to a multi-line
    call's first line, while the trailing comment often sits on the
    closing paren), and whether any finding consumed it."""

    codes: set[str]
    lines: set[int]
    comment_line: int
    used_codes: set[str] | None = None

    def __post_init__(self):
        if self.used_codes is None:
            self.used_codes = set()


def collect_suppressions(
    source: str,
) -> tuple[list[Suppression], list[int]]:
    """``# tpumt: ignore[TPM101,TPM201]`` comments plus the lines of
    malformed ``# tpumt:`` comments. Tokenized, not regexed over raw
    lines, so string literals containing the marker (e.g. this linter's
    own tests) cannot false-match."""
    supps: list[Suppression] = []
    malformed: list[int] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return supps, malformed
    _SKIP = (tokenize.NL, tokenize.NEWLINE, tokenize.COMMENT,
             tokenize.INDENT, tokenize.DEDENT, tokenize.ENCODING)
    logical_start: int | None = None
    for tok in tokens:
        if logical_start is None and tok.type not in _SKIP:
            logical_start = tok.start[0]
        if tok.type == tokenize.NEWLINE:
            logical_start = None
        if tok.type != tokenize.COMMENT or "tpumt:" not in tok.string:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        codes = {c.strip().upper() for c in m.group(1).split(",")
                 if c.strip()} if m else set()
        if codes:
            lines = {tok.start[0]}
            if logical_start is not None:
                lines.add(logical_start)
            supps.append(Suppression(codes, lines, tok.start[0]))
        else:
            malformed.append(tok.start[0])
    return supps, malformed


class CodeFilter:
    """``--select``/``--ignore`` semantics: comma lists of codes or
    family prefixes (``TPM1``, ``TPM1xx``, ``TPM101`` all work)."""

    def __init__(self, select: Iterable[str] | None = None,
                 ignore: Iterable[str] | None = None):
        self.select = self._norm(select)
        self.ignore = self._norm(ignore)

    @staticmethod
    def _norm(values: Iterable[str] | None) -> list[str]:
        out: list[str] = []
        for v in values or ():
            for piece in v.split(","):
                piece = piece.strip().upper()
                if piece.endswith("XX"):
                    piece = piece[:-2]
                if piece:
                    out.append(piece)
        return out

    def selected(self, code: str) -> bool:
        if self.select and not any(code.startswith(p) for p in self.select):
            return False
        return not any(code.startswith(p) for p in self.ignore)


def all_rules() -> list:
    """The registered rule instances (imported lazily so ``--help`` and
    suppression parsing never load the rule modules)."""
    from tpu_mpi_tests.analysis.rules import ALL_RULES

    return ALL_RULES


def rule_table() -> list[tuple[str, str]]:
    """``(code, summary)`` rows for every registered code, engine codes
    included — the ``--list-rules`` and README source of truth."""
    rows: list[tuple[str, str]] = []
    for rule in all_rules():
        rows.extend(sorted(rule.codes.items()))
    rows.extend(sorted(_ENGINE_CODES.items()))
    return rows


def iter_files(paths: Iterable[str]) -> Iterator[Path]:
    seen: set[Path] = set()
    for p in paths:
        path = Path(p)
        if path.is_dir():
            for f in sorted(path.rglob("*.py")):
                rel = f.relative_to(path)
                if any(part in SKIP_DIRS or part.startswith(".")
                       for part in rel.parts[:-1]):
                    continue
                if f not in seen:
                    seen.add(f)
                    yield f
        elif path.is_file() and path.suffix == ".py":
            if path not in seen:
                seen.add(path)
                yield path


def lint_paths(
    paths: Iterable[str],
    select: Iterable[str] | None = None,
    ignore: Iterable[str] | None = None,
    entry_modules: dict[str, str] | None = None,
) -> list[Finding]:
    """Lint files/directories; returns sorted, suppression-filtered
    findings (unused/malformed suppressions included as findings)."""
    code_filter = CodeFilter(select, ignore)
    contexts: list[FileContext] = []
    raw: set[Finding] = set()

    # a missing or non-.py path is a broken gate, never a clean one: a
    # renamed directory in the `make lint` path list must fail loudly,
    # not lint nothing and exit 0
    for p in paths:
        path = Path(p)
        if not path.exists():
            raw.add(Finding(str(p), 1, 0, "TPM902",
                            "path does not exist — a lint gate over a "
                            "missing path would pass vacuously"))
        elif path.is_file() and path.suffix != ".py":
            raw.add(Finding(str(p), 1, 0, "TPM902",
                            "not a python file"))

    for f in iter_files(paths):
        path = str(f)
        try:
            source = f.read_text()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError, ValueError) as e:
            line = getattr(e, "lineno", None) or 1
            raw.add(Finding(path, line, 0, "TPM902",
                            f"cannot parse: {e}"))
            continue
        contexts.append(FileContext(path, source, tree))

    rules = all_rules()
    for ctx in contexts:
        for rule in rules:
            if rule.scope != "file":
                continue
            for line, col, code, msg in rule.check(ctx):
                raw.add(Finding(ctx.path, line, col, code, msg))
    proj = ProjectContext(contexts, entry_modules or DEFAULT_ENTRY_MODULES)
    for rule in rules:
        if rule.scope != "project":
            continue
        for path, line, col, code, msg in rule.check_project(proj):
            raw.add(Finding(path, line, col, code, msg))

    suppressions = {
        ctx.path: collect_suppressions(ctx.source) for ctx in contexts
    }
    findings: list[Finding] = []
    for f in raw:
        if not code_filter.selected(f.code):
            continue
        matched = False
        for supp in suppressions.get(f.path, ((), ()))[0]:
            if f.line in supp.lines and f.code in supp.codes:
                supp.used_codes.add(f.code)
                matched = True
        if not matched:
            findings.append(f)

    for path, (supps, malformed) in suppressions.items():
        for supp in supps:
            for code in sorted(supp.codes - supp.used_codes):
                if not (code_filter.selected(code)
                        and code_filter.selected("TPM900")):
                    continue
                findings.append(Finding(
                    path, supp.comment_line, 0, "TPM900",
                    f"unused suppression for {code} — the finding it "
                    f"silenced is gone; remove the comment",
                ))
        for line in malformed:
            if code_filter.selected("TPM901"):
                findings.append(Finding(
                    path, line, 0, "TPM901",
                    "malformed tpumt comment — expected "
                    "`# tpumt: ignore[TPM101]` (comma-list of codes)",
                ))

    findings.sort()
    return findings
