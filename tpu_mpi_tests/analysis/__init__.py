"""Static analysis: ``tpumt-lint``, the repo's JAX/TPU correctness linter.

Encodes the host-side hazard classes this repo has shipped and fixed
(sync-dishonest timing, telemetry recorded under a jax trace, float64
values silently canonicalized to f32, eager ``import jax`` in login-node
CLIs, mesh-axis mismatches, unlocked cross-thread JSONL writes) as
mechanically-enforced AST rules with stable ``TPMxxx`` codes. The repo
itself must lint clean (``make lint``, part of ``make ci``).

Pure stdlib (``ast`` + ``tokenize``): like ``tpumt-report`` and
``tpumt-trace``, the linter is part of the login-node CLI set and must
import and run where ``import jax`` raises.
"""

# lazy re-exports (PEP 562), same discipline as the sibling packages:
# nothing here imports anything at module load beyond the stdlib, and the
# rule modules only load when the linter actually runs
_EXPORTS = {
    "Finding": "core",
    "lint_paths": "core",
    "all_rules": "core",
}
__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"tpu_mpi_tests.analysis.{_EXPORTS[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
