"""Content-hash-keyed analysis cache for ``tpumt-lint`` (ISSUE 10).

One JSON file (default ``~/.cache/tpumt/lint.json``, overridable via
``$TPU_MPI_LINT_CACHE`` / ``--cache``; ``--no-cache`` disables) mapping
each linted path to its last analysis: the sha256 of the file's bytes,
the file-scope findings it raised, its serialized whole-program facts
(:mod:`tpu_mpi_tests.analysis.program`), and its suppression comments.
A warm run replays all four for unchanged files — zero re-parsing — and
the project pass runs over the deserialized summaries, so whole-program
analysis stays incremental too.

Two invalidation axes, both automatic:

* **content**: the key is the file's hash — any edit (or a different
  file at the same path) misses;
* **engine**: the cache carries a *salt* hashed over the analysis
  package's own sources, so editing a rule or the extractor discards
  every entry at once (a rule change must re-judge every file — stale
  verdicts from an older rule set are worse than a cold run).

Corrupted/stale/unwritable cache files degrade to empty — the linter
never fails because its cache did (same contract as the tune cache).
Stdlib-only, like the rest of the analysis package.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path

#: bumped to 2 in ISSUE 13: the facts schema grew the threading-plane
#: keys (``races`` + per-function ``locks``) — a version-1 cache would
#: replay facts the project pass cannot judge. The engine salt would
#: catch this too (the analysis sources changed), but the version is
#: the explicit contract for the schema shape itself.
#: Bumped to 3 in ISSUE 18: per-function ``proto`` event trees +
#: ``rank_ret`` — the protocol layer (schedule automata, ``--conform``
#: replay, the doctor's ``--protocol-model``) rebuilds its whole
#: verdict from these cached facts, so a cache without them must read
#: as cold, never as "no schedule".
CACHE_VERSION = 3


def default_cache_path() -> str:
    env = os.environ.get("TPU_MPI_LINT_CACHE")
    if env:
        return env
    return str(Path.home() / ".cache" / "tpumt" / "lint.json")


def engine_salt() -> str:
    """Hash of the analysis package's own sources (fixtures excluded):
    any rule/extractor edit auto-invalidates every cached verdict."""
    h = hashlib.sha256()
    pkg = Path(__file__).resolve().parent
    for f in sorted(pkg.rglob("*.py")):
        if "fixtures" in f.parts or "__pycache__" in f.parts:
            continue
        h.update(str(f.relative_to(pkg)).encode())
        try:
            h.update(f.read_bytes())
        except OSError:
            pass
    return h.hexdigest()


class LintCache:
    """path → {hash, findings, facts, supps, malformed} with atomic
    merge-on-write saves. Keys are RESOLVED ABSOLUTE paths: relative
    keys would alias across working directories in a shared cache
    (the default lives under ``~/.cache``), and the stale-path eviction
    below could not tell "deleted" from "relative to somewhere else"."""

    @staticmethod
    def _key(path: str) -> str:
        try:
            return str(Path(path).resolve())
        except OSError:
            return str(path)

    def __init__(self, path: str):
        self.path = Path(path)
        self.salt = engine_salt()
        self._entries: dict[str, dict] = {}
        self._dirty = False
        self._entries = self._read(self.path)

    def _read(self, path: Path) -> dict[str, dict]:
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError):
            return {}
        if not isinstance(doc, dict):
            return {}
        if doc.get("version") != CACHE_VERSION or doc.get(
            "salt"
        ) != self.salt:
            return {}  # engine changed (or foreign format): cold start
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}

    def get(self, path: str, digest: str) -> dict | None:
        entry = self._entries.get(self._key(path))
        if not isinstance(entry, dict) or entry.get("hash") != digest:
            return None
        # shape/type validation happens at replay
        # (core.replay_cache_entry) — a hand-edited or type-corrupted
        # entry degrades to a miss there, never crashes the run
        return entry

    def put(self, path: str, digest: str, entry: dict) -> None:
        self._entries[self._key(path)] = {"hash": digest, **entry}
        self._dirty = True

    def save(self) -> None:
        # evict entries for deleted/renamed files (ISSUE 12 carry-over
        # nit): without this, stale paths accumulate until the next
        # engine-salt reset — a long-lived dev cache only ever grew
        stale = [p for p in self._entries if not Path(p).exists()]
        for p in stale:
            del self._entries[p]
            self._dirty = True
        if not self._dirty:
            return
        tmp = None
        try:
            # merge-on-write: concurrent linters over disjoint path sets
            # keep each other's entries (last writer wins per path);
            # the eviction filter applies to the on-disk side too
            merged = {
                p: e for p, e in self._read(self.path).items()
                if Path(p).exists()
            }
            merged.update(self._entries)
            self.path.parent.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=str(self.path.parent), suffix=".tmp"
            )
            with os.fdopen(fd, "w") as fh:
                json.dump({"version": CACHE_VERSION, "salt": self.salt,
                           "entries": merged}, fh)
            os.replace(tmp, self.path)
            tmp = None
        except OSError:
            pass  # an unwritable cache never fails the lint
        finally:
            if tmp is not None:
                try:  # failed write/replace: don't orphan the temp file
                    os.unlink(tmp)
                except OSError:
                    pass
