"""Whole-program layer for ``tpumt-lint`` (ISSUE 10 tentpole).

Turns the per-file lexical linter into an interprocedural analyzer:
:func:`extract_facts` distills each parsed file into a JSON-serializable
*facts* record — module-level imports (the TPM4xx graph edges), axis
bindings/uses (TPM5xx), dispatch-less timed regions (TPM1xx), donation
data flow (TPM12xx), and one bottom-up summary per function:

* **dispatches** — the body (own scope, nested defs excluded) calls into
  jax / the comm / kernels layers or a local compiled-fn;
* **syncs** — it calls a ``block``/``block_until_ready``/``comm_span``-
  class synchronizer;
* **events** — the ordered sequence of collective dispatches and
  outgoing calls (the call-graph edges plus the TPM11xx comparison
  alphabet);
* **donates** — positional params donated via ``donate_argnums`` or
  forwarded into a donated position of a callee (one helper level by
  summary composition);
* **returns_handle** — it returns an ``async_span`` dispatch-window
  handle (directly or through another returning helper);
* **rank_ifs** — branches guarded by rank-dependent control flow
  (``process_index()`` / ``rank == 0`` comparisons, truthiness tests
  like ``if not rank:``, and locals aliasing a ``process_index()``
  call) with each *path's* event sequence computed over the function's
  control-flow graph (:mod:`tpu_mpi_tests.analysis.cfg`): a ``return``
  or ``raise`` inside a branch truncates that path, so the events after
  the join belong only to the paths that actually reach them — the
  TPM1101/TPM1102 split. Each branch also carries the names bound on
  exactly one side and their first read on the other path (the TPM1301
  broadcast-consistency input);

* **record contract** — per file, the JSONL record schemas its dict
  literals *produce* (keyed by their constant ``kind``, ``**``-spreads
  and ``.update()`` marking the schema open) and the record fields its
  functions *consume* (``rec.get("...")``/subscripts on a variable
  whose ``kind`` the function tested) — the TPM14xx input and the
  ``RECORDS.md`` source of truth.

:class:`ProjectIndex` is the project-scope view: a module symbol table
over every linted file's facts plus memoized transitive resolution
(call-graph closure) for the properties above. Facts round-trip through
JSON unchanged, which is what makes the analysis cache
(:mod:`tpu_mpi_tests.analysis.lintcache`) able to skip parse + summary
for unchanged files while the project pass still sees the whole program.

Known limits (documented in README "Static analysis"): resolution is
name-based — dynamic dispatch, method calls through objects, ``*args``
forwarding (except the sanctioned ``span_call``/``DispatchWindow.call``
shapes) and handles stored into containers are invisible to the
summaries. The rules built on top are conservative accordingly.

Stdlib-only by contract, like the rest of the analysis package.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_mpi_tests.analysis import cfg as cfg_mod
from tpu_mpi_tests.analysis.core import (
    FileContext,
    attr_parts,
    device_callables,
    is_device_call,
    last_attr,
    own_nodes as _own_nodes,
    stmt_lists,
    walk_calls,
)

# ---------------------------------------------------------------------------
# shared vocabularies (the sync-honesty constants live here so both the
# file-scope rule and the facts extractor read ONE definition without
# the extractor importing the rule registry)

#: clock reads that start/stop a timing region
CLOCKS = {"time.perf_counter", "time.monotonic"}

#: call targets (final component) that synchronize device work before the
#: clock is read again — chain_rate/dispatch_rate embed the discipline
SYNC_NAMES = {
    "block", "block_until_ready", "comm_span", "span_call", "timed",
    "host_value", "device_get", "chain_rate", "dispatch_rate",
    "sync_global_devices", "barrier",
}

#: calls whose string literals BIND axis names for a file (TPM5xx)
AXIS_DEF_CALLS = {
    "shard_map", "Mesh", "AbstractMesh", "make_mesh", "NamedSharding",
    "PartitionSpec", "P",
}

#: collective/axis-query calls checked, with the axis argument position
AXIS_USES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "ppermute": 1, "all_gather": 1, "all_to_all": 1, "pshuffle": 1,
    "pbroadcast": 1, "axis_index": 0, "axis_size": 0,
    "pcast_varying": 1, "pcast": 1,
}

#: origins whose AXIS_USES calls are real collectives (a local helper
#: coincidentally named `all_gather` is not checked)
USE_ORIGINS = ("jax", "tpu_mpi_tests.compat")

#: final-name vocabulary of collective dispatch points for the TPM11xx
#: divergence alphabet: the jax host-level collectives plus this repo's
#: comm-layer wrappers (every one of them enters an operation ALL ranks
#: of the mesh must enter together)
COLLECTIVE_CALLS = {
    # jax / multihost
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "ppermute",
    "pshuffle", "pbroadcast", "all_to_all", "process_allgather",
    # tpu_mpi_tests.comm wrappers
    "all_gather", "all_gather_rdma", "all_gather_inplace",
    "allreduce_sum", "allreduce_rdma", "reduce_scatter_sum",
    "reduce_sum", "barrier", "halo_exchange", "ring_attention",
    "ulysses_attention", "route_tokens", "embedding_lookup",
    "embedding_scatter_add", "per_rank_sums", "per_rank_err_norms",
}

#: origin prefixes a resolved collective call must come from — a local
#: helper that happens to share a name resolves through its own summary
#: instead
COLLECTIVE_ORIGINS = ("jax", "tpu_mpi_tests")

#: repo wrappers known to donate positional arguments (TPM12xx): every
#: one jits its payload with ``donate_argnums=0`` under the hood — the
#: ``x = allreduce(x)`` in-place idiom. Position → donated.
KNOWN_DONATING = {
    "allreduce_sum": (0,),
    "allreduce_rdma": (0,),
    "all_gather_inplace": (0,),
    "reduce_scatter_sum": (0,),
    "halo_exchange": (0,),
    "embedding_scatter_add": (0,),
}

#: call shapes that forward ``*args`` to a callee passed at position 1
#: (``span_call(op, fn, *args)`` / ``DispatchWindow.call(op, fn, *args)``)
#: — the donating-chain plumbing ISSUE 7 made pervasive
FORWARDER_CALLS = {"span_call", "call"}

#: calls that mint an async dispatch-window handle (TPM8xx)
HANDLE_SOURCES = {"async_span"}

#: names whose mention in an `if` test makes the branch rank-dependent
RANK_NAMES = {"rank", "proc", "proc_index", "process_index", "pidx",
              "rank_id"}
#: rank names too ambiguous for the TRUTHINESS widening: `proc` is
#: commonly a subprocess handle, and `if not self.proc:` is a liveness
#: check, not a rank test — these still match in comparisons
#: (`proc == 0`), never as bare mentions
AMBIGUOUS_RANK_NAMES = {"proc"}
#: call targets (final component) in an `if` test that read the rank
RANK_CALLS = {"process_index"}

#: call targets (final component) that replicate a rank-local value to
#: every rank — the sanctioned exits from a rank-guarded binding before
#: per-rank work may consume it (TPM1301's allowlist)
BROADCAST_CALLS = {
    "broadcast", "broadcast_one_to_all", "pbroadcast",
    "process_allgather", "bcast",
}

#: telemetry span emitters (ISSUE 18): a call to one of these with a
#: CONSTANT first argument is a statically-known runtime ``(op, axis)``
#: event — the exact alphabet ``kind:"span"`` records carry — so the
#: protocol layer derives its schedule automaton from the emitters
#: themselves instead of guessing a wrapper→runtime-op table. A
#: dynamic first argument (``self.op``, f-strings) is recorded with
#: ``op=None``: a span whose name the static model cannot know.
SPAN_EMITTERS = {"comm_span", "span_call", "async_span"}

# summary-expansion recursion bound, not a device schedule knob — there
# is nothing to tune and no topology it varies with
_MAX_DEPTH = 16  # tpumt: ignore[TPM701]


# ---------------------------------------------------------------------------
# small walkers


def _walk_functions_cls(
    tree: ast.Module,
) -> list[tuple[str, ast.AST, str]]:
    """``(qualname, node, enclosing_class_qual)`` for every def, in
    document order — nested defs and methods get dotted qualnames
    (``outer.inner``, ``Cls.meth``); the class qual is ``""`` for
    plain/nested functions (the lockset layer needs to know which
    ``self`` an access belongs to)."""
    out: list[tuple[str, ast.AST, str]] = []

    def visit(node: ast.AST, prefix: str, cls: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child, cls))
                visit(child, q + ".", "")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.",
                      f"{prefix}{child.name}")
            else:
                visit(child, prefix, cls)

    visit(tree, "", "")
    return out


def _walk_functions(
    tree: ast.Module,
) -> list[tuple[str, ast.AST]]:
    """``(qualname, node)`` for every def, in document order."""
    return [(q, n) for q, n, _cls in _walk_functions_cls(tree)]


def canon_target(ctx: FileContext, func: ast.AST) -> str | None:
    """Canonical dotted target of a call: import origins substituted and
    relative imports resolved against the file's module, so the project
    index can look the name up. None for non-name-rooted calls."""
    resolved = ctx.imports.resolve(func)
    if not resolved:
        return None
    if resolved.startswith("."):
        resolved = _resolve_relative(
            resolved, ctx.module, ctx.path.endswith("__init__.py")
        )
    return resolved


def _is_collective(canon: str | None, last: str | None) -> bool:
    if not canon or last not in COLLECTIVE_CALLS:
        return False
    return canon.startswith(COLLECTIVE_ORIGINS)


# ---------------------------------------------------------------------------
# module-level imports (the TPM4xx graph edges; hoisted from
# rules/import_hygiene so facts extraction owns the single definition)


def _resolve_relative(module: str, current: str, is_pkg: bool) -> str:
    """``.foo``/``..foo`` against the importing module's package."""
    level = len(module) - len(module.lstrip("."))
    name = module[level:]
    parts = current.split(".") if current else []
    if not is_pkg:
        parts = parts[:-1]
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
    return ".".join(parts + ([name] if name else []))


def _catches_import_error(stmt: ast.Try) -> bool:
    for h in stmt.handlers:
        if h.type is None:
            return True  # bare except
        types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for t in types:
            name = getattr(t, "id", None) or getattr(t, "attr", None)
            if name in ("ImportError", "ModuleNotFoundError",
                        "Exception", "BaseException"):
                return True
    return False


def module_level_imports(
    ctx: FileContext,
) -> list[list]:
    """``[line, module, from_names]`` for every import executed at module
    import time: top-level statements plus those nested in module-level
    ``if``/``try`` (conditional imports still run), but nothing inside a
    function or class body (lazy by construction), nothing under an
    ``if TYPE_CHECKING:`` guard (never runs), and nothing in a
    ``try: ... except ImportError:`` body (the canonical safe optional
    import — handler bodies are still scanned)."""
    out: list[list] = []
    is_pkg = ctx.path.endswith("__init__.py")

    def scan(stmts):
        for stmt in stmts:
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    out.append([stmt.lineno, a.name, []])
            elif isinstance(stmt, ast.ImportFrom):
                mod = ("." * stmt.level) + (stmt.module or "")
                if mod.startswith("."):
                    mod = _resolve_relative(mod, ctx.module, is_pkg)
                out.append([stmt.lineno, mod,
                            [a.name for a in stmt.names]])
            elif isinstance(stmt, ast.If):
                if any(
                    isinstance(n, (ast.Name, ast.Attribute))
                    and (getattr(n, "id", None) == "TYPE_CHECKING"
                         or getattr(n, "attr", None) == "TYPE_CHECKING")
                    for n in ast.walk(stmt.test)
                ):
                    continue
                scan(stmt.body)
                scan(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                if not _catches_import_error(stmt):
                    scan(stmt.body)
                scan(stmt.orelse)
                scan(stmt.finalbody)
                for h in stmt.handlers:
                    scan(h.body)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                scan(stmt.body)

    scan(ctx.tree.body)
    return out


# ---------------------------------------------------------------------------
# timed regions (the TPM1xx detector, shared with rules/sync_honesty)


def _clock_assign(ctx: FileContext, stmt: ast.stmt) -> str | None:
    """``t0 = time.perf_counter()`` → ``"t0"``; else None."""
    if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)):
        return None
    if ctx.imports.resolve(stmt.value.func) in CLOCKS:
        return stmt.targets[0].id
    return None


def _uses_in_sub(stmt: ast.stmt, name: str) -> bool:
    """Does the statement read the clock delta (``... - t0``)?"""
    for n in ast.walk(stmt):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
            for side in (n.left, n.right):
                if isinstance(side, ast.Name) and side.id == name:
                    return True
    return False


def _rebinds(stmt: ast.stmt, name: str) -> bool:
    if isinstance(stmt, ast.Assign):
        return any(isinstance(t, ast.Name) and t.id == name
                   for t in stmt.targets)
    return False


def iter_timed_regions(ctx: FileContext) -> Iterator[list[ast.stmt]]:
    """Every clock-pair region in the file: the statements between a
    ``t0 = perf_counter()`` assignment and the first read of its delta
    (``... - t0``), inclusive. A rebind of the clock name before any
    delta read abandons the region (clock restarted)."""
    for stmts in stmt_lists(ctx.tree):
        for i, stmt in enumerate(stmts):
            t = _clock_assign(ctx, stmt)
            if not t:
                continue
            region: list[ast.stmt] = []
            for j in range(i + 1, len(stmts)):
                region.append(stmts[j])
                if _uses_in_sub(stmts[j], t):
                    yield region
                    break
                if _rebinds(stmts[j], t):
                    break  # clock restarted before any delta read


# ---------------------------------------------------------------------------
# facts extraction


def _rank_dependent(test: ast.AST,
                    extra_names: frozenset | set = frozenset()) -> bool:
    """Is this `if` test a function of the process rank? Conservative:
    a ``process_index()`` call anywhere in it, a comparison whose side
    is a rank-named variable/attribute (``rank == 0``,
    ``topo.process_index != 0``), or a bare truthiness mention
    (``if not rank:``, ``if rank:``) of an UNAMBIGUOUS rank name. The
    lexical engine only matched Compare sides, which is how
    ``if not rank:`` shipped as a documented TPM1101 false negative;
    the ambiguous names (``proc`` — usually a subprocess handle) keep
    the comparison-only behavior so liveness checks don't convict.
    ``extra_names`` carries the function's local ``process_index()``
    aliases."""
    cmp_names = RANK_NAMES | set(extra_names)
    truthy_names = cmp_names - AMBIGUOUS_RANK_NAMES
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            if (last_attr(n.func) or "") in RANK_CALLS:
                return True
        elif isinstance(n, ast.Compare):
            for side in [n.left] + list(n.comparators):
                name = None
                if isinstance(side, ast.Name):
                    name = side.id
                elif isinstance(side, ast.Attribute):
                    name = side.attr
                if name in cmp_names:
                    return True
        elif isinstance(n, ast.Name):
            if n.id in truthy_names and isinstance(n.ctx, ast.Load):
                return True
        elif isinstance(n, ast.Attribute):
            if n.attr in truthy_names:
                return True
    return False


def _rank_aliases(node: ast.AST) -> set[str]:
    """Local names that hold the process rank: assigned from a
    ``process_index()``-class call (``r = jax.process_index()``, the
    walrus form included) or pure aliases of a rank name (``r = rank``).
    Document-order scan, so alias chains resolve."""
    out: set[str] = set()

    def value_is_rank(v: ast.AST) -> bool:
        # DIRECT forms only: `r = rank`, `r = topo.process_index`,
        # `r = jax.process_index()`. A rank call merely nested in the
        # value (`rep = Reporter(proc_index=process_index())`) must NOT
        # taint the whole assigned object as a rank.
        if isinstance(v, ast.Name):
            return v.id in RANK_NAMES or v.id in out
        if isinstance(v, ast.Attribute):
            return v.attr in RANK_NAMES
        if isinstance(v, ast.Call):
            return (last_attr(v.func) or "") in RANK_CALLS
        return False

    for n in _own_nodes(node):
        if isinstance(n, ast.Assign) and value_is_rank(n.value):
            out.update(t.id for t in n.targets
                       if isinstance(t, ast.Name))
        elif isinstance(n, ast.AnnAssign) and n.value is not None \
                and isinstance(n.target, ast.Name) \
                and value_is_rank(n.value):
            out.add(n.target.id)
        elif isinstance(n, ast.NamedExpr) and value_is_rank(n.value):
            if isinstance(n.target, ast.Name):
                out.add(n.target.id)
    return out


def _unit_nodes(unit: ast.AST) -> Iterator[ast.AST]:
    """The unit (a simple statement or a test/iter expression) plus its
    own-scope subtree."""
    yield unit
    yield from _own_nodes(unit)


# ---------------------------------------------------------------------------
# protocol facts (ISSUE 18): the structured event tree the schedule
# automaton and the TPM17xx checks are compiled from


def _const_str(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _taint_sources(ctx: FileContext, node: ast.AST) -> dict[str, str]:
    """Local name → canonical call target it was assigned from — the
    return-value taint channel (``mode = pick_mode()`` where
    ``pick_mode`` turns out to be rank-returning assembles a
    rank-divergent branch no lexical rank test reveals). A name EVER
    rebound from a broadcast-class call is dropped entirely: the sweep's
    ``go = fleet.bcast(go, ...)`` replication is exactly what makes the
    value rank-invariant again."""
    out: dict[str, str] = {}
    killed: set[str] = set()
    for n in _own_nodes(node):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            canon = canon_target(ctx, n.value.func)
            last = last_attr(n.value.func) or ""
            for t in n.targets:
                if not isinstance(t, ast.Name):
                    continue
                if last in BROADCAST_CALLS:
                    killed.add(t.id)
                elif canon:
                    out[t.id] = canon
    for name in killed:
        out.pop(name, None)
    return out


def _test_taints(ctx: FileContext, expr: ast.AST,
                 sources: dict[str, str]) -> list[str]:
    """Canonical targets whose return value feeds this test: calls made
    inside it plus the assigned-from targets of names it reads. Judged
    rank-returning (or not) at project time, where the callee summaries
    exist."""
    canons: set[str] = set()
    for n in _unit_nodes(expr):
        if isinstance(n, ast.Call):
            c = canon_target(ctx, n.func)
            if c:
                canons.add(c)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            c = sources.get(n.id)
            if c:
                canons.add(c)
    return sorted(canons)[:8]


def _returns_rank(node: ast.AST, aliases: set[str]) -> bool:
    """Does the function return the process rank? DIRECT forms only
    (mirrors ``_rank_aliases``): ``return rank``, ``return self.rank``,
    ``return jax.process_index()``. A rank merely nested in a returned
    constructor call does not make the whole object a rank."""
    names = RANK_NAMES | aliases
    for n in _own_nodes(node):
        if not isinstance(n, ast.Return) or n.value is None:
            continue
        v = n.value
        if isinstance(v, ast.Name) and v.id in names:
            return True
        if isinstance(v, ast.Attribute) and v.attr in RANK_NAMES:
            return True
        if isinstance(v, ast.Call) and (last_attr(v.func) or "") \
                in RANK_CALLS:
            return True
    return False


def _terminates(stmts: list[ast.stmt]) -> bool:
    """Does this straight-line statement list always leave the enclosing
    block (return/raise/break/continue on every path)? Conservative:
    only the shapes that matter for branch-summary truncation."""
    for s in stmts:
        if isinstance(s, (ast.Return, ast.Raise, ast.Break,
                          ast.Continue)):
            return True
        if isinstance(s, ast.If) and s.orelse and _terminates(s.body) \
                and _terminates(s.orelse):
            return True
        if isinstance(s, (ast.With, ast.AsyncWith)) \
                and _terminates(s.body):
            return True
    return False


def _proto_tree(ctx: FileContext, node: ast.AST, aliases: set[str],
                sources: dict[str, str]) -> list:
    """The function body as a structured event tree — the ISSUE-18
    ``proto`` fact. Node shapes (JSON lists, cache-stable):

    * ``["coll", op, canon, line, core]`` — a lexical collective call
      (``core`` 1 for the TPM11xx alphabet, 0 for broadcast-class
      replication points, which TPM1101 deliberately cannot see);
    * ``["span", op|None, axis|None, line]`` — a telemetry span
      emitter: the runtime event a ``kind:"span"`` record witnesses
      (``op None`` = dynamically named);
    * ``["call", canon, line]`` — a resolvable outgoing call;
    * ``["loop", line, rank, taints, body]`` — ``for``/``while`` with
      the bound's rank-dependence (lexical bit + taint candidates);
    * ``["alt", line, col, rank, taints, then, orelse]`` — a branch;
    * ``["try", line, body, [[terminates, handler_body], ...]]``;
    * ``["exit", line]`` — return/raise/break/continue.
    """

    def classify(call: ast.Call) -> list | None:
        last = last_attr(call.func)
        canon = canon_target(ctx, call.func) or ""
        if last in SPAN_EMITTERS and canon.startswith("tpu_mpi_tests"):
            axis = None
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    axis = _const_str(kw.value)
            op = _const_str(call.args[0]) if call.args else None
            return ["span", op, axis, call.lineno]
        if last == "call" and len(call.args) >= 2 \
                and _const_str(call.args[0]) is not None:
            # DispatchWindow.call(op, fn, *args): dispatch + drain emit
            # spans under that constant op name
            return ["span", _const_str(call.args[0]), None, call.lineno]
        if _is_collective(canon, last):
            return ["coll", last, canon, call.lineno, 1]
        if canon and last in BROADCAST_CALLS \
                and canon.startswith(COLLECTIVE_ORIGINS):
            return ["coll", last, canon, call.lineno, 0]
        if canon:
            return ["call", canon, call.lineno]
        return None

    def expr_events(expr: ast.AST | None) -> list:
        if expr is None:
            return []
        out = []
        for n in _unit_nodes(expr):
            if isinstance(n, ast.Call):
                ev = classify(n)
                if ev is not None:
                    out.append(ev)
        return out

    def loop_node(s, bound: ast.AST, body: list[ast.stmt]) -> list:
        rk = 1 if _rank_dependent(bound, aliases) else 0
        taints = [] if rk else _test_taints(ctx, bound, sources)
        return ["loop", s.lineno, rk, taints, walk(body)]

    def walk(stmts: list[ast.stmt]) -> list:
        out: list = []
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.If):
                out.extend(expr_events(s.test))
                rk = 1 if _rank_dependent(s.test, aliases) else 0
                taints = [] if rk else _test_taints(ctx, s.test, sources)
                out.append(["alt", s.lineno, s.col_offset, rk, taints,
                            walk(s.body), walk(s.orelse)])
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                out.extend(expr_events(s.iter))
                out.append(loop_node(s, s.iter, s.body))
                out.extend(walk(s.orelse))
            elif isinstance(s, ast.While):
                out.extend(expr_events(s.test))
                out.append(loop_node(s, s.test, s.body))
                out.extend(walk(s.orelse))
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    out.extend(expr_events(item.context_expr))
                out.extend(walk(s.body))
            elif isinstance(s, ast.Try):
                body = walk(s.body) + walk(s.orelse)
                handlers = [
                    [1 if _terminates(h.body) else 0, walk(h.body)]
                    for h in s.handlers
                ]
                out.append(["try", s.lineno, body, handlers])
                out.extend(walk(s.finalbody))
            elif isinstance(s, (ast.Return, ast.Raise)):
                out.extend(expr_events(getattr(s, "value", None)
                                       or getattr(s, "exc", None)))
                out.append(["exit", s.lineno])
            elif isinstance(s, (ast.Break, ast.Continue)):
                out.append(["exit", s.lineno])
            else:
                out.extend(expr_events(s))
        return out

    return walk(list(getattr(node, "body", [])))


def _path_events(ctx: FileContext, graph: cfg_mod.CFG,
                 entry: cfg_mod.Block) -> list:
    """Ordered ``["coll", op]`` / ``["call", target]`` events along the
    forward paths from ``entry`` to the function exit (loops unrolled
    once). Unlike the old lexical branch events, a path that ``return``s
    early simply does not contain the events after the join."""
    ev: list = []
    for block in graph.reachable(entry):
        for unit in block.units:
            for n in _unit_nodes(unit):
                if not isinstance(n, ast.Call):
                    continue
                canon = canon_target(ctx, n.func)
                last = last_attr(n.func)
                if _is_collective(canon, last):
                    ev.append(["coll", last])
                elif canon:
                    ev.append(["call", canon])
    return ev


def _real_bound(stmts: list[ast.stmt]) -> set[str]:
    """Names meaningfully bound in a branch body (own scope): every
    Store target except pure ``= None`` placeholders — ``winner = None``
    on the unguarded side is the *absence* of a value, which is exactly
    what TPM1301 needs to see through. Per STORE SITE, not per name: a
    name that is None-initialized and then really bound in the same
    branch (``winner = None`` … ``winner = fallback()``) is bound."""
    none_targets: set[int] = set()
    real: set[str] = set()
    for s in stmts:
        for n in _unit_nodes(s):
            if isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Constant
            ) and n.value.value is None:
                none_targets.update(
                    id(t) for t in n.targets
                    if isinstance(t, ast.Name)
                )
            elif isinstance(n, ast.AnnAssign) and isinstance(
                n.value, ast.Constant
            ) and n.value.value is None and isinstance(
                n.target, ast.Name
            ):
                # `winner: T = None` — the annotated placeholder form
                none_targets.add(id(n.target))
    for s in stmts:
        for n in _unit_nodes(s):
            if isinstance(n, ast.Name) and isinstance(
                n.ctx, ast.Store
            ) and id(n) not in none_targets:
                real.add(n.id)
    return real


def _first_reads(graph: cfg_mod.CFG, entry: cfg_mod.Block,
                 names: set[str],
                 exclude: set[int] = frozenset()) -> list[list]:
    """First Load of each name along the forward paths from ``entry``:
    ``[name, line, col, enclosing_call]`` where ``enclosing_call`` is
    the final attr of the call the name is a DIRECT argument of (the
    broadcast-allowlist witness), or None. Blocks in ``exclude`` (the
    exclusive regions of OTHER rank guards — a read there only runs on
    some ranks, usually the same rank-0 that bound the value) are not
    scanned. A rebind of the name ON THE SCANNED PATH before any read
    (``plan = load_cached(...)`` on every rank) kills the one-sided
    value — reads after it see the rebound value and are safe."""
    out: dict[str, list] = {}
    dead: set[str] = set()
    for block in graph.reachable(entry):
        if block.idx in exclude:
            continue
        for unit in block.units:
            callmap: dict[int, str | None] = {}
            for n in _unit_nodes(unit):
                if not isinstance(n, ast.Call):
                    continue
                target = last_attr(n.func)
                for a in list(n.args) + [
                    kw.value for kw in n.keywords
                ]:
                    if isinstance(a, ast.Name):
                        callmap[id(a)] = target
            # loads first (an RHS read in `plan = f(plan)` happens
            # before the rebind), then stores kill the name — except
            # `= None` placeholder stores (the unguarded arm's
            # `winner = None` is the absence the rule exists to see)
            none_ids: set[int] = set()
            aug_ids: set[int] = set()
            for n in _unit_nodes(unit):
                if isinstance(n, ast.Assign) and isinstance(
                    n.value, ast.Constant
                ) and n.value.value is None:
                    none_ids.update(id(t) for t in n.targets)
                elif isinstance(n, ast.AnnAssign) and isinstance(
                    n.value, ast.Constant
                ) and n.value.value is None:
                    none_ids.add(id(n.target))
                elif isinstance(n, ast.AugAssign):
                    # `w += 1` READS the old value (its target has
                    # Store ctx only): a read site, never a kill
                    aug_ids.add(id(n.target))
            for n in _unit_nodes(unit):
                is_aug_read = id(n) in aug_ids
                if isinstance(n, ast.Name) and (
                    isinstance(n.ctx, ast.Load) or is_aug_read
                ) and n.id in names and n.id not in out \
                        and n.id not in dead:
                    out[n.id] = [n.id, n.lineno, n.col_offset,
                                 callmap.get(id(n))]
            for n in _unit_nodes(unit):
                if isinstance(n, ast.Name) and isinstance(
                    n.ctx, ast.Store
                ) and n.id in names and id(n) not in none_ids \
                        and id(n) not in aug_ids:
                    dead.add(n.id)
    return sorted(out.values())


def _rank_if_facts(ctx: FileContext, node: ast.AST,
                   graph: cfg_mod.CFG | None = None) -> list[dict]:
    """Every rank-dependent ``if`` in the function as a flow-sensitive
    fact: path-to-exit event sequences, early-exit bits, and the
    one-side-bound names with their first unguarded-path read."""
    aliases = _rank_aliases(node)
    if graph is None:
        graph = cfg_mod.build(node)
    # pre-branch stores, with the `= None` placeholder filter applied
    # per site (a `winner = None` BEFORE the rank guard is the same
    # absence-of-a-value as one in the else arm)
    none_targets: set[int] = set()
    for n in _own_nodes(node):
        if isinstance(n, ast.Assign) and isinstance(
            n.value, ast.Constant
        ) and n.value.value is None:
            none_targets.update(id(t) for t in n.targets)
        elif isinstance(n, ast.AnnAssign) and isinstance(
            n.value, ast.Constant
        ) and n.value.value is None:
            none_targets.add(id(n.target))
    before_lines: list[tuple[int, str]] = [
        (n.lineno, n.id) for n in _own_nodes(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
        and id(n) not in none_targets
    ]
    # every-rank bindings that exist before any branch runs: ALL
    # parameter kinds (a kwonly/vararg/kwarg refreshed under a rank
    # guard is still bound everywhere) and imported names (a module
    # alias monkeypatched on rank 0 exists on every rank regardless)
    a = node.args
    always_bound = {p.arg for p in (a.posonlyargs + a.args
                                    + a.kwonlyargs)}
    for va in (a.vararg, a.kwarg):
        if va is not None:
            always_bound.add(va.arg)
    always_bound |= set(ctx.imports.modules) | set(ctx.imports.names)
    rank_branches = [
        br for br in graph.branches
        if _rank_dependent(br.node.test, aliases)
    ]
    # blocks exclusively inside SOME rank guard: a read there executes
    # only on the ranks that take that guard — reading a rank-0-bound
    # value under another rank-0 test is the idiomatic rank-0-only
    # reporter shape, not a divergence (conservative: a mismatched
    # guard rank is a false negative, never a false positive)
    gated_per_branch: dict[int, set[int]] = {}
    gated_all: set[int] = set()
    for br in rank_branches:
        rt = {b.idx for b in graph.reachable(br.then_entry)}
        re_ = {b.idx for b in graph.reachable(br.else_entry)}
        exc = (rt - re_) | (re_ - rt)
        gated_per_branch[id(br)] = exc
        gated_all |= exc

    out: list[dict] = []
    for br in rank_branches:
        s = br.node
        bound_then = _real_bound(s.body)
        bound_else = _real_bound(s.orelse)
        bound_before = set(always_bound) | {
            name for line, name in before_lines if line < s.lineno
        }
        only_then = bound_then - bound_else - bound_before
        only_else = bound_else - bound_then - bound_before
        other_gated = gated_all - gated_per_branch[id(br)]
        unbcast: list[list] = []
        if only_then:
            unbcast.extend(
                _first_reads(graph, br.else_entry, only_then,
                             exclude=other_gated)
            )
        if only_else:
            unbcast.extend(
                _first_reads(graph, br.then_entry, only_else,
                             exclude=other_gated)
            )
        out.append({
            "line": s.lineno, "col": s.col_offset,
            "then": _path_events(ctx, graph, br.then_entry),
            "orelse": _path_events(ctx, graph, br.else_entry),
            "then_exits": br.then_exits,
            "else_exits": br.else_exits,
            "unbcast": sorted(unbcast),
        })
    return out


def _donate_positions(node: ast.AST) -> list[int]:
    """``donate_argnums`` positions from the def's decorators (the
    ``functools.partial(jax.jit, donate_argnums=...)`` idiom included)."""
    pos: set[int] = set()
    for dec in node.decorator_list:
        for n in ast.walk(dec):
            if not isinstance(n, ast.Call):
                continue
            for kw in n.keywords:
                if kw.arg != "donate_argnums":
                    continue
                v = kw.value
                vals = v.elts if isinstance(
                    v, (ast.Tuple, ast.List)
                ) else [v]
                for e in vals:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, int
                    ):
                        pos.add(e.value)
    return sorted(pos)


def _function_facts(ctx: FileContext, qual: str, node: ast.AST,
                    local_device: set[str],
                    graph: cfg_mod.CFG | None = None) -> dict:
    params = [a.arg for a in (node.args.posonlyargs + node.args.args)]
    pidx = {p: i for i, p in enumerate(params)}
    aliases = _rank_aliases(node)
    dispatches = syncs = returns_handle = False
    events: list = []
    forwards: list = []
    return_targets: list[str] = []
    handle_names: set[str] = set()
    assigned_calls: list[list] = []
    loads = {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }

    for n in _own_nodes(node):
        if isinstance(n, ast.Call):
            canon = canon_target(ctx, n.func)
            last = last_attr(n.func)
            if last in SYNC_NAMES:
                syncs = True
            if is_device_call(ctx, n, local_device):
                dispatches = True
            if _is_collective(canon, last):
                events.append(["coll", last])
            elif canon:
                events.append(["call", canon])
            if canon is None:
                continue
            if (last in FORWARDER_CALLS and len(n.args) > 1
                    and isinstance(n.args[1], ast.Name)):
                inner = canon_target(ctx, n.args[1])
                for i, a in enumerate(n.args[2:], start=2):
                    if isinstance(a, ast.Name) and a.id in pidx and inner:
                        forwards.append([pidx[a.id], inner, i - 2])
            else:
                for i, a in enumerate(n.args):
                    if isinstance(a, ast.Name) and a.id in pidx:
                        forwards.append([pidx[a.id], canon, i])
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            canon = canon_target(ctx, n.value.func)
            tnames = [t.id for t in n.targets if isinstance(t, ast.Name)]
            if not canon:
                continue
            if canon.rsplit(".", 1)[-1] in HANDLE_SOURCES:
                handle_names.update(tnames)
            else:
                for t in tnames:
                    assigned_calls.append(
                        [t, canon, n.lineno, n.col_offset]
                    )
        elif isinstance(n, ast.Return) and n.value is not None:
            v = n.value
            if isinstance(v, ast.Call):
                canon = canon_target(ctx, v.func)
                if canon and canon.rsplit(".", 1)[-1] in HANDLE_SOURCES:
                    returns_handle = True
                elif canon:
                    return_targets.append(canon)
            elif isinstance(v, ast.Name) and v.id in handle_names:
                returns_handle = True

    return {
        "name": qual,
        "line": node.lineno,
        "params": params,
        "donates": _donate_positions(node),
        "dispatches": dispatches,
        "syncs": syncs,
        "events": events,
        "forwards": forwards,
        "returns_handle": returns_handle,
        "return_targets": return_targets,
        "rank_ifs": _rank_if_facts(ctx, node, graph),
        # unconsumed call-result handles: assigned, then never read —
        # the TPM802 candidates (a name loaded ANYWHERE in the def,
        # nested closures included, counts as consumed)
        "handle_drops": [a for a in assigned_calls if a[0] not in loads],
        # ISSUE 18: the structured event tree (loops, branches, try
        # blocks, span emitters) the protocol layer compiles into the
        # schedule automaton, plus the return-value rank taint bit
        "proto": _proto_tree(ctx, node, aliases,
                             _taint_sources(ctx, node)),
        "rank_ret": _returns_rank(node, aliases),
    }


def _axis_facts(ctx: FileContext) -> tuple[list[str], list[list]]:
    bound: set[str] = set()
    for call in walk_calls(ctx.tree):
        if last_attr(call.func) in AXIS_DEF_CALLS:
            for n in ast.walk(call):
                if isinstance(n, ast.Constant) and isinstance(
                    n.value, str
                ):
                    bound.add(n.value)
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(
                    v.value, str
                ):
                    bound.add(v.value)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    bound.update(
                        e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    )

    uses: list[list] = []
    for call in walk_calls(ctx.tree):
        name = last_attr(call.func)
        if name not in AXIS_USES:
            continue
        chain = attr_parts(call.func)
        if not chain:
            continue
        origin = ctx.imports.origin(chain[0]) or ""
        if not origin.startswith(USE_ORIGINS):
            continue
        axis_arg = None
        pos = AXIS_USES[name]
        if len(call.args) > pos:
            axis_arg = call.args[pos]
        else:
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    axis_arg = kw.value
        if axis_arg is None:
            continue
        lits = []
        if isinstance(axis_arg, ast.Constant) and isinstance(
            axis_arg.value, str
        ):
            lits.append((axis_arg.value, axis_arg))
        elif isinstance(axis_arg, (ast.Tuple, ast.List)):
            lits.extend(
                (e.value, e) for e in axis_arg.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)
            )
        for axis, anode in lits:
            uses.append([anode.lineno, anode.col_offset, name, axis])
    return sorted(bound), uses


def _timed_region_facts(ctx: FileContext,
                        local_device: set[str]) -> list[dict]:
    """Regions TPM101 cannot judge alone: no sync, no DIRECT dispatch —
    but outgoing calls whose summaries may dispatch (TPM102's input)."""
    out: list[dict] = []
    for region in iter_timed_regions(ctx):
        calls: list[list] = []
        has_sync = has_direct = False
        for stmt in region:
            for call in walk_calls(stmt):
                if last_attr(call.func) in SYNC_NAMES:
                    has_sync = True
                    break
                if is_device_call(ctx, call, local_device):
                    has_direct = True
                    continue
                canon = canon_target(ctx, call.func)
                if canon:
                    calls.append([canon, call.lineno, call.col_offset])
            if has_sync:
                break
        if not has_sync and not has_direct and calls:
            out.append({"calls": calls})
    return out


def _dflow_facts(ctx: FileContext) -> list[dict]:
    """Donation data flow: per statement list, each statement's calls
    (with positional arg names), subsequent reads and rebinds of those
    arg names — enough for TPM1201's read-after-donate scan without
    keeping the tree around.

    Two scope/flow guards keep the scan honest: a ``def``/``class``
    statement contributes nothing to its ENCLOSING list (its body is a
    different scope — same-named locals in sibling functions are
    unrelated), and a donating call under a ``return``/``raise`` is not
    recorded (control exits the list, so no later statement runs on
    that path — the ``if host_staged: return span_call(zg, ...)``
    dispatch-fork idiom is safe by construction)."""
    loop_bodies: set[int] = set()
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
            loop_bodies.add(id(n.body))

    nested_skip: dict[int, list[ast.AST]] = {}

    def stmt_own(stmt: ast.stmt) -> list[ast.AST]:
        if id(stmt) not in nested_skip:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                nested_skip[id(stmt)] = []  # its own scope, not ours
            else:
                nested_skip[id(stmt)] = [stmt] + list(_own_nodes(stmt))
        return nested_skip[id(stmt)]

    out: list[dict] = []
    for stmts in stmt_lists(ctx.tree):
        per_stmt_calls: list[list[dict]] = []
        arg_names: set[str] = set()
        for stmt in stmts:
            calls: list[dict] = []
            exiting: set[int] = set()
            for n in stmt_own(stmt):
                if isinstance(n, (ast.Return, ast.Raise)):
                    exiting.update(id(w) for w in ast.walk(n))
            for n in stmt_own(stmt):
                if not isinstance(n, ast.Call) or id(n) in exiting:
                    continue
                canon = canon_target(ctx, n.func)
                if not canon:
                    continue
                args = [a.id if isinstance(a, ast.Name) else None
                        for a in n.args]
                if not any(args):
                    continue
                fwd = None
                if (canon.rsplit(".", 1)[-1] in FORWARDER_CALLS
                        and len(n.args) > 1
                        and isinstance(n.args[1], ast.Name)):
                    fwd = canon_target(ctx, n.args[1])
                calls.append({"line": n.lineno, "col": n.col_offset,
                              "target": canon, "args": args,
                              "fwd": fwd})
                arg_names.update(a for a in args if a)
            per_stmt_calls.append(calls)
        if not arg_names:
            continue
        entries: list[dict] = []
        for stmt, calls in zip(stmts, per_stmt_calls):
            reads: list[list] = []
            binds: set[str] = set()
            seen_read: set[str] = set()
            for n in stmt_own(stmt):
                if not isinstance(n, ast.Name) or n.id not in arg_names:
                    continue
                if isinstance(n.ctx, ast.Load):
                    if n.id not in seen_read:
                        seen_read.add(n.id)
                        reads.append([n.id, n.lineno])
                elif isinstance(n.ctx, ast.Store):
                    binds.add(n.id)
            entries.append({"line": stmt.lineno, "calls": calls,
                            "reads": reads, "binds": sorted(binds)})
        out.append({"loop": id(stmts) in loop_bodies, "stmts": entries})
    return out


# ---------------------------------------------------------------------------
# record-contract facts (TPM14xx / RECORDS.md)


#: sink chokepoints a record dict flows through verbatim — the
#: Reporter's JSONL writer and the telemetry registry's raw emit
SINK_CALLS = {"jsonl", "emit"}


def _record_producer_facts(
    ctx: FileContext,
) -> tuple[list[list], list[list]]:
    """``(schemas, stamps)`` — every JSONL record schema the file
    produces plus the envelope fields its sink wrappers stamp on.

    A *schema* is a dict literal / ``dict(...)`` call carrying a
    constant-string ``kind``, as ``[kind, event, fields, open, line]``.
    Fields include constant subscript stores on the name the dict was
    assigned to (``rec["phase"] = ...`` — the memwatch build-up idiom).
    ``open`` marks schemas with dynamic parts — a ``**spread``, a
    non-constant key/subscript, or a later ``.update()`` on the name
    (the ``CommEvent.record`` meta idiom) — which the field check must
    not judge.

    A *stamp* is ``[fields, line]`` from a dict literal that has a
    ``**spread`` but NO ``kind`` of its own and is passed directly into
    a ``jsonl``/``emit`` sink call — the
    ``rep.jsonl({**rec, "rank": rep.proc_index})`` envelope idiom:
    every record flowing through the wrapper gains those fields, so
    they are available on every kind.

    The name-linked idioms (build-up stores, ``.update()``) resolve
    PER SCOPE — module level, or one function's own nodes: two
    functions both calling their local record ``rec`` must not bleed
    fields or open-ness into each other's kinds."""
    schemas: list[list] = []
    stamps: list[list] = []
    scopes = [list(_own_nodes(ctx.tree))] + [
        list(_own_nodes(fn))
        for _qual, fn in _walk_functions(ctx.tree)
    ]
    for nodes in scopes:
        s, st = _scope_producer_facts(nodes)
        schemas.extend(s)
        stamps.extend(st)
    schemas.sort(key=lambda r: (r[0], r[1] or "", r[4]))
    stamps.sort(key=lambda r: r[1])
    return schemas, stamps


def _scope_producer_facts(
    nodes: list[ast.AST],
) -> tuple[list[list], list[list]]:
    updated: set[str] = set()
    sub_stores: dict[str, set[str]] = {}
    dyn_stores: set[str] = set()
    dict_targets: dict[int, list[str]] = {}
    sink_args: set[int] = set()
    for n in nodes:
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "update" \
                    and isinstance(n.func.value, ast.Name):
                updated.add(n.func.value.id)
            if (last_attr(n.func) or "") in SINK_CALLS:
                sink_args.update(id(a) for a in n.args)
        elif isinstance(n, ast.Assign) and isinstance(
            n.value, ast.Dict
        ):
            dict_targets[id(n.value)] = [
                t.id for t in n.targets if isinstance(t, ast.Name)
            ]
        elif isinstance(n, ast.AnnAssign) and isinstance(
            n.value, ast.Dict
        ) and isinstance(n.target, ast.Name):
            # `rec: dict[str, Any] = {...}` — the annotated form of
            # the same build-up idiom
            dict_targets[id(n.value)] = [n.target.id]
        elif isinstance(n, ast.Subscript) and isinstance(
            n.ctx, ast.Store
        ) and isinstance(n.value, ast.Name):
            if isinstance(n.slice, ast.Constant) and isinstance(
                n.slice.value, str
            ):
                sub_stores.setdefault(n.value.id, set()).add(
                    n.slice.value
                )
            else:
                dyn_stores.add(n.value.id)

    schemas: list[list] = []
    stamps: list[list] = []
    for n in nodes:
        kind = event = None
        fields: set[str] = set()
        open_ = has_spread = False
        if isinstance(n, ast.Dict):
            for k, v in zip(n.keys, n.values):
                if k is None:  # **spread
                    open_ = has_spread = True
                    continue
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    open_ = True
                    continue
                fields.add(k.value)
                if isinstance(v, ast.Constant) and isinstance(
                    v.value, str
                ):
                    if k.value == "kind":
                        kind = v.value
                    elif k.value == "event":
                        event = v.value
            for t in dict_targets.get(id(n), ()):
                fields.update(sub_stores.get(t, ()))
                if t in updated or t in dyn_stores:
                    open_ = True
        elif isinstance(n, ast.Call) and last_attr(n.func) == "dict":
            for kw in n.keywords:
                if kw.arg is None:  # **spread
                    open_ = has_spread = True
                    continue
                fields.add(kw.arg)
                if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, str
                ):
                    if kw.arg == "kind":
                        kind = kw.value.value
                    elif kw.arg == "event":
                        event = kw.value.value
        else:
            continue
        if kind is not None:
            schemas.append([kind, event, sorted(fields - {"kind"}),
                            open_, n.lineno])
        elif has_spread and "kind" not in fields and fields \
                and id(n) in sink_args:
            stamps.append([sorted(fields), n.lineno])
    return schemas, stamps


def _kind_access_var(n: ast.AST) -> str | None:
    """``X.get("kind")`` / ``X["kind"]`` → ``"X"``; else None."""
    if isinstance(n, ast.Call) and isinstance(
        n.func, ast.Attribute
    ) and n.func.attr == "get" and isinstance(
        n.func.value, ast.Name
    ) and n.args and isinstance(n.args[0], ast.Constant) \
            and n.args[0].value == "kind":
        return n.func.value.id
    if isinstance(n, ast.Subscript) and isinstance(
        n.value, ast.Name
    ) and isinstance(n.slice, ast.Constant) \
            and n.slice.value == "kind":
        return n.value.id
    return None


_KIND_CMP_OPS = (ast.Eq, ast.NotEq, ast.In, ast.NotIn)


def _field_access(n: ast.AST) -> tuple[str, str] | None:
    """``X.get("field", ...)`` / ``X["field"]`` (Load) →
    ``(var, field)``; else None."""
    if isinstance(n, ast.Call) and isinstance(
        n.func, ast.Attribute
    ) and n.func.attr == "get" and isinstance(
        n.func.value, ast.Name
    ) and n.args and isinstance(n.args[0], ast.Constant) \
            and isinstance(n.args[0].value, str):
        return n.func.value.id, n.args[0].value
    if isinstance(n, ast.Subscript) and isinstance(
        n.value, ast.Name
    ) and isinstance(n.slice, ast.Constant) and isinstance(
        n.slice.value, str
    ) and isinstance(n.ctx, ast.Load):
        return n.value.id, n.slice.value
    return None


def _kind_compares(expr: ast.AST, alias: dict[str, str]) -> list:
    """Every kind test inside an expression:
    ``(recvar, consts, positive)`` — ``rec.get("kind") == "span"``,
    ``kind in ("a", "b")`` through a ``kind = rec.get("kind")`` alias,
    and the negative forms (``!=`` / ``not in``)."""
    out: list = []
    for n in ast.walk(expr):
        if not isinstance(n, ast.Compare) or len(n.ops) != 1:
            continue
        op = n.ops[0]
        if not isinstance(op, _KIND_CMP_OPS):
            continue
        recvar = None
        consts: list[str] = []
        for side in [n.left] + list(n.comparators):
            rv = _kind_access_var(side)
            if rv:
                recvar = rv
            elif isinstance(side, ast.Name) and side.id in alias:
                recvar = alias[side.id]
            elif isinstance(side, ast.Constant) and isinstance(
                side.value, str
            ):
                consts.append(side.value)
            elif isinstance(side, (ast.Tuple, ast.List, ast.Set)):
                consts.extend(
                    e.value for e in side.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
        if recvar and consts:
            positive = isinstance(op, (ast.Eq, ast.In))
            out.append((recvar, consts, positive, n.lineno))
    return out


def _record_consumer_facts(
    ctx: FileContext,
    graphs: dict[int, cfg_mod.CFG] | None = None,
) -> list[dict]:
    """Per function: each record variable whose ``kind`` the function
    tests against string constants (directly, or through a
    ``kind = rec.get("kind")`` alias — the dominant consumer idiom) and
    the constant fields it reads off that variable, *flow-sensitively
    attributed* over the CFG:

    * a read in the blocks exclusively reachable from a kind test's
      TRUE edge (its ``elif`` arm, say) belongs to exactly the kinds
      that test established — the big per-kind dispatch loops judge
      each arm against its own schema, not the union;
    * a read exclusively on the FALSE side of a positive test (the
      ``else:`` of ``if h.get("kind") == "finding":``) is governed by
      an unknown complement schema and is skipped — negative tests
      (``!= "span"``) govern their false side instead;
    * a read in shared code (before the dispatch, after the join, or
      inside a comprehension the statement CFG cannot split) falls back
      to the union of every kind the function tested.

    Output: ``{"var", "kinds", "line", "groups": [{"kinds", "fields"}]}``
    where an empty group ``kinds`` means the union fallback.
    """
    out: list[dict] = []
    for _qual, fn in _walk_functions(ctx.tree):
        nodes = list(_own_nodes(fn))
        alias: dict[str, str] = {}
        for n in nodes:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                rv = _kind_access_var(n.value)
                if rv:
                    alias[n.targets[0].id] = rv

        all_kinds: dict[str, set[str]] = {}
        klines: dict[str, int] = {}
        for recvar, consts, _pos, line in _kind_compares(fn, alias):
            all_kinds.setdefault(recvar, set()).update(consts)
            klines.setdefault(recvar, line)
        if not all_kinds:
            continue

        graph = (graphs or {}).get(id(fn)) or cfg_mod.build(fn)
        # var -> block idx -> governing kinds (positive regions); and
        # var -> block idxs whose schema is an unknown complement
        governed: dict[str, dict[int, set[str]]] = {}
        skipped: dict[str, set[int]] = {}
        # test-expression units attribute their own reads to their own
        # positive kinds (`elif kind == "serve" and rec.get("event")..`)
        test_kinds: dict[int, dict[str, set[str]]] = {}
        for br in graph.branches:
            cmps = _kind_compares(br.node.test, alias)
            if not cmps:
                continue
            reach_then = {b.idx for b in graph.reachable(br.then_entry)}
            reach_else = {b.idx for b in graph.reachable(br.else_entry)}
            exc_then = reach_then - reach_else
            exc_else = reach_else - reach_then
            for recvar, consts, positive, _line in cmps:
                gov_region, skip_region = (
                    (exc_then, exc_else) if positive
                    else (exc_else, exc_then)
                )
                gv = governed.setdefault(recvar, {})
                for idx in gov_region:
                    gv.setdefault(idx, set()).update(consts)
                skipped.setdefault(recvar, set()).update(skip_region)
                if positive:
                    test_kinds.setdefault(id(br.node.test), {}) \
                        .setdefault(recvar, set()).update(consts)

        # group reads: frozenset of governing kinds (empty = union)
        groups: dict[str, dict[frozenset, dict[str, list]]] = {
            v: {} for v in all_kinds
        }
        for block in graph.blocks:
            for unit in block.units:
                tk = test_kinds.get(id(unit), {})
                for n in _unit_nodes(unit):
                    acc = _field_access(n)
                    if not acc:
                        continue
                    var, fname = acc
                    if var not in all_kinds or fname == "kind":
                        continue
                    if var in tk:
                        key = frozenset(tk[var])
                    else:
                        gov = governed.get(var, {}).get(block.idx)
                        if gov:
                            key = frozenset(gov)
                        elif block.idx in skipped.get(var, ()):
                            continue  # unknown complement schema
                        else:
                            key = frozenset()  # union fallback
                    groups[var].setdefault(key, {}).setdefault(
                        fname, [fname, n.lineno, n.col_offset]
                    )
        for var in sorted(all_kinds):
            out.append({
                "var": var,
                "kinds": sorted(all_kinds[var]),
                "line": klines[var],
                "groups": [
                    {"kinds": sorted(key),
                     "fields": sorted(fields.values())}
                    for key, fields in sorted(
                        groups[var].items(),
                        key=lambda kv: sorted(kv[0]),
                    )
                    if fields
                ],
            })
    return out


def extract_facts(ctx: FileContext) -> dict:
    """The file's whole-program facts record — pure data, JSON-stable
    (cold extraction and a cache round-trip produce identical project
    findings)."""
    from tpu_mpi_tests.analysis.locks import extract_race_facts

    local_device = device_callables(ctx)
    axis_bound, axis_uses = _axis_facts(ctx)
    rec_produced, rec_stamps = _record_producer_facts(ctx)
    # one CFG per function, shared by the rank-branch, record-consumer,
    # and lockset passes (they walk the same function list)
    functions_cls = _walk_functions_cls(ctx.tree)
    graphs = {id(node): cfg_mod.build(node)
              for _qual, node, _cls in functions_cls}
    races, fn_locks = extract_race_facts(
        ctx, functions_cls, graphs,
        resolve=lambda func: canon_target(ctx, func),
    )
    out_functions = []
    for qual, node, _cls in functions_cls:
        fn = _function_facts(ctx, qual, node, local_device,
                             graphs[id(node)])
        fn["locks"] = fn_locks.get(id(node), {})
        out_functions.append(fn)
    return {
        "path": ctx.path,
        "module": ctx.module,
        "mod_imports": module_level_imports(ctx),
        "axis_bound": axis_bound,
        "axis_uses": axis_uses,
        "timed_regions": _timed_region_facts(ctx, local_device),
        "dflow": _dflow_facts(ctx),
        "rec_produced": rec_produced,
        "rec_stamps": rec_stamps,
        "rec_consumed": _record_consumer_facts(ctx, graphs),
        "races": races,
        "functions": out_functions,
    }


# ---------------------------------------------------------------------------
# project index


class ProjectIndex:
    """Module symbol table + call graph over the linted facts, with
    memoized transitive resolution of the per-function summaries."""

    def __init__(self, facts_list: list[dict]):
        self.facts = facts_list
        self.functions: dict[str, list[dict]] = {}
        self._fn_module: dict[int, str] = {}
        self._fn_by_module: dict[str, list[tuple[str, dict]]] = {}
        for ff in facts_list:
            for fn in ff["functions"]:
                key = f'{ff["module"]}.{fn["name"]}' if ff["module"] \
                    else fn["name"]
                self.functions.setdefault(key, []).append(fn)
                self._fn_module[id(fn)] = ff["module"]
                self._fn_by_module.setdefault(
                    ff["module"], []
                ).append((fn["name"], fn))
        self._memo: dict[tuple, bool] = {}

    # -- resolution --------------------------------------------------------

    def resolve_funcs(self, target: str | None,
                      module: str) -> list[dict]:
        """Facts for a canonical call target seen from ``module``: an
        exact module-qualified match first, then (for bare names) any
        same-module nested def with that final name — bare calls to
        closures are common in driver bodies and skipping them would
        blind every interprocedural family to the dominant local-helper
        idiom."""
        if not target:
            return []
        if "." in target:
            return self.functions.get(target, [])
        exact = self.functions.get(
            f"{module}.{target}" if module else target, []
        )
        if exact:
            return exact
        suffix = f".{target}"
        return [fn for name, fn in self._fn_by_module.get(module, [])
                if name.endswith(suffix)]

    def _module_of(self, fn: dict) -> str:
        return self._fn_module.get(id(fn), "")

    # -- transitive summaries ---------------------------------------------

    def _trans(self, fn: dict, key: str, direct) -> bool:
        memo_key = (key, id(fn))
        if memo_key in self._memo:
            return self._memo[memo_key]
        self._memo[memo_key] = False  # cycle guard
        val = direct(fn)
        if not val:
            mod = self._module_of(fn)
            for kind, target in fn["events"]:
                if kind != "call":
                    continue
                if any(self._trans(g, key, direct)
                       for g in self.resolve_funcs(target, mod)):
                    val = True
                    break
        self._memo[memo_key] = val
        return val

    def dispatches(self, fn: dict) -> bool:
        """Does this function's call graph dispatch device work?"""
        return self._trans(
            fn, "disp",
            lambda f: f["dispatches"]
            or any(e[0] == "coll" for e in f["events"]),
        )

    def syncs(self, fn: dict) -> bool:
        """Does its call graph reach a block/comm_span-class sync?"""
        return self._trans(fn, "sync", lambda f: f["syncs"])

    def returns_handle(self, fn: dict) -> bool:
        """Does it return an async_span handle (directly or through a
        returning helper)?"""
        memo_key = ("handle", id(fn))
        if memo_key in self._memo:
            return self._memo[memo_key]
        self._memo[memo_key] = False
        val = fn["returns_handle"]
        if not val:
            mod = self._module_of(fn)
            for target in fn["return_targets"]:
                if any(self.returns_handle(g)
                       for g in self.resolve_funcs(target, mod)):
                    val = True
                    break
        self._memo[memo_key] = val
        return val

    # -- collective sequences (TPM11xx) ------------------------------------

    def collective_seq(self, events: list, module: str,
                       _depth: int = 0,
                       _stack: frozenset = frozenset()) -> list[str]:
        """Flatten an event list into the ordered collective-op sequence
        its execution dispatches, expanding calls through the summaries
        (first match per target; cycle- and depth-guarded)."""
        if _depth > _MAX_DEPTH:
            return []
        out: list[str] = []
        for kind, val in events:
            if kind == "coll":
                out.append(val)
                continue
            funcs = self.resolve_funcs(val, module)
            if not funcs:
                continue
            g = funcs[0]
            if id(g) in _stack:
                continue
            out.extend(self.collective_seq(
                g["events"], self._module_of(g), _depth + 1,
                _stack | {id(g)},
            ))
        return out

    # -- donation (TPM12xx) -------------------------------------------------

    def call_donates(self, target: str | None, module: str,
                     _depth: int = 0) -> set[int]:
        """Donated positional-argument positions of a call to
        ``target``: the curated comm-wrapper table plus any project
        function's effective donations (its own ``donate_argnums`` or a
        param forwarded into a donated position of ITS callee — the
        one-helper-level composition)."""
        out: set[int] = set()
        if not target or _depth > 3:
            return out
        last = target.rsplit(".", 1)[-1]
        if last in KNOWN_DONATING and (
            target == last or target.startswith("tpu_mpi_tests")
        ):
            out.update(KNOWN_DONATING[last])
        for fn in self.resolve_funcs(target, module):
            out.update(fn["donates"])
            mod = self._module_of(fn)
            for ppos, fwd_target, cpos in fn["forwards"]:
                if cpos in self.call_donates(fwd_target, mod, _depth + 1):
                    out.add(ppos)
        return out

    def site_donates(self, call: dict, module: str) -> set[int]:
        """Donated positions at a recorded dflow call site, the
        span_call/DispatchWindow.call forwarding shape included (callee
        at arg 1, payload from arg 2 on)."""
        out = set(self.call_donates(call["target"], module))
        if call.get("fwd"):
            out |= {p + 2
                    for p in self.call_donates(call["fwd"], module)}
        return out
