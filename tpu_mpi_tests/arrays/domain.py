"""Domain decomposition: ghost-cell layouts and local↔global index math.

The reference re-derives this arithmetic inline in every stencil driver
(`mpi_stencil_gt.cc:152-196`, `mpi_stencil2d_gt.cc:395-497`); here it is one
tested component. Conventions match the reference exactly so error norms are
comparable:

* the global domain is ``[0, length)`` sampled at ``n_global`` points with
  spacing ``delta = length / n_global`` (`mpi_stencil_gt.cc:166-168`);
* shard ``r`` owns interior points ``r*n_local .. (r+1)*n_local - 1``;
* each shard carries ``n_bnd`` ghost points on both sides of the decomposed
  axis; interior ghosts are filled by halo exchange, *physical* ghosts on the
  first/last shard are filled analytically so non-periodic error norms are
  discretization-only (`mpi_stencil_gt.cc:185-196`,
  `mpi_stencil2d_gt.cc:458-497`).

Global representation for single-controller drivers: the "ghosted global"
array is the concatenation of the per-shard ghosted blocks along the
decomposed axis — shape ``n_shards * (n_local + 2*n_bnd)`` there. Sharded
over a mesh axis, each device holds exactly its ghosted local block, which is
the reference's per-rank array layout.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from tpu_mpi_tests.utils import check_divisible


@dataclasses.dataclass(frozen=True)
class Domain1D:
    """1-D decomposed domain (≅ mpi_stencil_gt.cc sizing block :152-168)."""

    n_global: int
    n_shards: int
    n_bnd: int = 2
    length: float = 8.0

    def __post_init__(self):
        check_divisible(self.n_global, self.n_shards, "Domain1D n_global")

    @property
    def n_local(self) -> int:
        return self.n_global // self.n_shards

    @property
    def delta(self) -> float:
        return self.length / self.n_global

    @property
    def scale(self) -> float:
        """1/delta — the stencil scale factor (`mpi_stencil_gt.cc:168`)."""
        return self.n_global / self.length

    @property
    def n_ghosted(self) -> int:
        return self.n_local + 2 * self.n_bnd

    def interior_coords(self, rank: int, dtype=np.float64) -> np.ndarray:
        x0 = rank * (self.length / self.n_shards)
        return x0 + np.arange(self.n_local, dtype=dtype) * self.delta

    def ghosted_coords(self, rank: int, dtype=np.float64) -> np.ndarray:
        """Coordinates for the full ghosted block, including what physical or
        halo-filled ghosts *should* contain (ghosts continue the global grid,
        which for edge shards extends past [0, length))."""
        x0 = rank * (self.length / self.n_shards)
        idx = np.arange(-self.n_bnd, self.n_local + self.n_bnd, dtype=dtype)
        return x0 + idx * self.delta

    def init_shard(
        self, fn: Callable[[np.ndarray], np.ndarray], rank: int, dtype=np.float64
    ) -> np.ndarray:
        """Ghosted local block with interior = fn(x); interior ghosts zero;
        physical ghosts on edge shards filled analytically."""
        out = np.zeros(self.n_ghosted, dtype=dtype)
        out[self.n_bnd : self.n_bnd + self.n_local] = fn(
            self.interior_coords(rank, dtype)
        )
        xg = self.ghosted_coords(rank, dtype)
        if rank == 0:
            out[: self.n_bnd] = fn(xg[: self.n_bnd])
        if rank == self.n_shards - 1:
            out[-self.n_bnd :] = fn(xg[-self.n_bnd :])
        return out

    def init_shard_jax(self, fn, rank, dtype):
        """Traceable ghosted-shard init (device-side; ``rank`` may be a
        traced index) — same layout as :meth:`init_shard`."""
        import jax.numpy as jnp

        start = jnp.asarray(rank, dtype) * (self.n_local * self.delta)
        idx = jnp.arange(-self.n_bnd, self.n_local + self.n_bnd, dtype=dtype)
        x = start + idx * self.delta
        full = fn(x).astype(dtype)
        i = jnp.arange(self.n_ghosted)
        keep = (
            ((i >= self.n_bnd) & (i < self.n_bnd + self.n_local))
            | ((i < self.n_bnd) & (rank == 0))
            | ((i >= self.n_bnd + self.n_local)
               & (rank == self.n_shards - 1))
        )
        return jnp.where(keep, full, jnp.zeros((), dtype))

    def interior_shard_jax(self, fn, rank, dtype):
        """Traceable unghosted-shard field (device-side err references)."""
        import jax.numpy as jnp

        start = jnp.asarray(rank, dtype) * (self.n_local * self.delta)
        idx = jnp.arange(self.n_local, dtype=dtype)
        return fn(start + idx * self.delta).astype(dtype)

    def init_global(self, fn, dtype=np.float64) -> np.ndarray:
        """Ghosted-global concatenation of all shard blocks."""
        return np.concatenate(
            [self.init_shard(fn, r, dtype) for r in range(self.n_shards)]
        )

    def interior_global(self, fn, dtype=np.float64) -> np.ndarray:
        """Unghosted global field fn(x) — reference values for err norms."""
        return np.concatenate(
            [fn(self.interior_coords(r, dtype)) for r in range(self.n_shards)]
        )

    def strip_ghosts_global(self, zg: np.ndarray) -> np.ndarray:
        """Drop ghost points from a ghosted-global array → unghosted global."""
        blocks = zg.reshape(self.n_shards, self.n_ghosted)
        return blocks[:, self.n_bnd : self.n_bnd + self.n_local].reshape(-1)


@dataclasses.dataclass(frozen=True)
class Domain2D:
    """2-D array decomposed along one axis (≅ mpi_stencil2d_gt.cc:395-417).

    ``dim`` is the decomposed/derivative axis (0 or 1); the other axis is
    global on every shard. Sizes follow the reference: the decomposed axis is
    weak-scaled (``n_local_deriv`` per shard), the other axis is fixed
    globally (`mpi_stencil2d_gt.cc:656,675-676`).
    """

    n_local_deriv: int
    n_global_other: int
    n_shards: int
    dim: int = 0
    n_bnd: int = 2
    length: float = 8.0

    def __post_init__(self):
        if self.dim not in (0, 1):
            raise ValueError(f"dim must be 0 or 1, got {self.dim}")

    @property
    def n_global_deriv(self) -> int:
        return self.n_local_deriv * self.n_shards

    @property
    def delta(self) -> float:
        return self.length / self.n_global_deriv

    @property
    def scale(self) -> float:
        return self.n_global_deriv / self.length

    @property
    def local_shape(self) -> tuple[int, int]:
        s = [0, 0]
        s[self.dim] = self.n_local_deriv
        s[1 - self.dim] = self.n_global_other
        return tuple(s)

    @property
    def ghosted_shape(self) -> tuple[int, int]:
        s = list(self.local_shape)
        s[self.dim] += 2 * self.n_bnd
        return tuple(s)

    @property
    def global_ghosted_shape(self) -> tuple[int, int]:
        s = list(self.ghosted_shape)
        s[self.dim] *= self.n_shards
        return tuple(s)

    @property
    def global_interior_shape(self) -> tuple[int, int]:
        s = list(self.local_shape)
        s[self.dim] *= self.n_shards
        return tuple(s)

    def _coords(self, rank: int, ghosted: bool, dtype):
        """(x, y) 1-D coordinate vectors for this shard's block."""
        start = rank * self.n_local_deriv * self.delta
        if ghosted:
            idx = np.arange(
                -self.n_bnd, self.n_local_deriv + self.n_bnd, dtype=dtype
            )
        else:
            idx = np.arange(self.n_local_deriv, dtype=dtype)
        deriv_c = start + idx * self.delta
        other_c = np.arange(self.n_global_other, dtype=dtype) * self.delta
        return (deriv_c, other_c) if self.dim == 0 else (other_c, deriv_c)

    def init_shard(self, fn, rank: int, dtype=np.float64) -> np.ndarray:
        """Ghosted local block; interior = fn(x, y) on the shard grid;
        physical ghosts analytic on edge shards, interior ghosts zero."""
        x, y = self._coords(rank, ghosted=True, dtype=dtype)
        full = fn(x[:, None], y[None, :]).astype(dtype)
        out = np.zeros(self.ghosted_shape, dtype=dtype)
        sl = [slice(None), slice(None)]
        sl[self.dim] = slice(self.n_bnd, self.n_bnd + self.n_local_deriv)
        out[tuple(sl)] = full[tuple(sl)]
        if rank == 0:
            lo = [slice(None), slice(None)]
            lo[self.dim] = slice(0, self.n_bnd)
            out[tuple(lo)] = full[tuple(lo)]
        if rank == self.n_shards - 1:
            hi = [slice(None), slice(None)]
            hi[self.dim] = slice(self.n_bnd + self.n_local_deriv, None)
            out[tuple(hi)] = full[tuple(hi)]
        return out

    def init_global(self, fn, dtype=np.float64) -> np.ndarray:
        return np.concatenate(
            [self.init_shard(fn, r, dtype) for r in range(self.n_shards)],
            axis=self.dim,
        )

    def _coords_jax(self, rank, ghosted: bool, dtype):
        """(x, y) coordinate vectors with a possibly-traced ``rank`` —
        device-side init (host→device transfer of multi-GB analytic fields
        is absurd when the device can compute them; measured 333 s for a
        2.2 GB shard over a tunneled controller vs milliseconds on chip)."""
        import jax.numpy as jnp

        start = jnp.asarray(rank, dtype) * (self.n_local_deriv * self.delta)
        if ghosted:
            idx = jnp.arange(
                -self.n_bnd, self.n_local_deriv + self.n_bnd, dtype=dtype
            )
        else:
            idx = jnp.arange(self.n_local_deriv, dtype=dtype)
        deriv_c = start + idx * self.delta
        other_c = jnp.arange(self.n_global_other, dtype=dtype) * self.delta
        return (
            (deriv_c, other_c) if self.dim == 0 else (other_c, deriv_c)
        )

    def init_shard_jax(self, fn, rank, dtype):
        """Traceable ghosted-shard init (``rank`` may be a traced index):
        interior = fn, physical ghosts analytic on edge shards, interior
        ghosts zero — same layout as :meth:`init_shard`, computed on
        device."""
        import jax.numpy as jnp

        x, y = self._coords_jax(rank, ghosted=True, dtype=dtype)
        full = fn(x[:, None], y[None, :]).astype(dtype)
        i = jnp.arange(self.n_local_deriv + 2 * self.n_bnd)
        interior = (i >= self.n_bnd) & (i < self.n_bnd + self.n_local_deriv)
        keep = (
            interior
            | ((i < self.n_bnd) & (rank == 0))
            | ((i >= self.n_bnd + self.n_local_deriv)
               & (rank == self.n_shards - 1))
        )
        shape = [1, 1]
        shape[self.dim] = keep.shape[0]
        return jnp.where(keep.reshape(shape), full, jnp.zeros((), dtype))

    def interior_shard_jax(self, fn, rank, dtype):
        """Traceable unghosted-shard field — device-side err-norm
        reference values."""
        x, y = self._coords_jax(rank, ghosted=False, dtype=dtype)
        return fn(x[:, None], y[None, :]).astype(dtype)

    def interior_shard(self, fn, rank: int, dtype=np.float64) -> np.ndarray:
        """One rank's unghosted block of fn(x, y) — per-rank err-norm
        reference values (the global field is never materialized)."""
        x, y = self._coords(rank, ghosted=False, dtype=dtype)
        return fn(x[:, None], y[None, :]).astype(dtype)

    def interior_global(self, fn, dtype=np.float64) -> np.ndarray:
        """Unghosted global field fn(x, y) — err-norm reference values."""
        return np.concatenate(
            [self.interior_shard(fn, r, dtype) for r in range(self.n_shards)],
            axis=self.dim,
        )

    def strip_ghosts_global(self, zg: np.ndarray) -> np.ndarray:
        ng = self.ghosted_shape[self.dim]
        blocks = np.split(zg, self.n_shards, axis=self.dim)
        sl = [slice(None), slice(None)]
        sl[self.dim] = slice(self.n_bnd, ng - self.n_bnd)
        return np.concatenate([b[tuple(sl)] for b in blocks], axis=self.dim)
