"""Memory spaces: the TPU analog of device / managed / pinned-host memory.

The reference's memory-space axis (SURVEY.md §2.3 last row) is explicit
`cudaMalloc` vs `cudaMallocManaged` vs `cudaMallocHost`, selected per-build
(`-DMANAGED`, `mpi_daxpy_nvtx.cc:178-198`) or per-test
(`TEST_MANAGED` matrix, `mpi_stencil2d_gt.cc:696-728`), with `MEMINFO`
introspection (`cuda_error.h:99-136`).

On TPU the axes map to JAX memory kinds:

* ``DEVICE``   → HBM (default ``"device"`` memory kind).
* ``HOST``     → ``"pinned_host"`` memory kind when the backend supports it
  (TPU does); arrays stay addressable by XLA but live in host RAM.
* ``MANAGED``  → no direct analog (TPU has no page-migrating unified memory);
  the closest semantics — "usable from both sides, runtime moves it" — is
  host-resident data with implicit transfer on use. We implement it as
  pinned-host placement when available, else plain host numpy handed to jit
  (committed-on-use), and record the deviation explicitly.

`meminfo` replaces the MEMINFO macro: it reports where an array actually
lives.
"""

from __future__ import annotations

import enum
import functools

import jax
import numpy as np

from tpu_mpi_tests.utils import TpuMtError


class Space(enum.Enum):
    """Placement space for benchmark arrays (≅ gtensor spaces)."""

    DEVICE = "device"
    HOST = "host"
    MANAGED = "managed"

    @classmethod
    def parse(cls, s: "str | Space") -> "Space":
        if isinstance(s, Space):
            return s
        try:
            return cls[s.upper()]
        except KeyError:
            raise TpuMtError(
                f"unknown space {s!r}; valid: "
                f"{[m.name.lower() for m in cls]}"
            ) from None


@functools.cache
def _supported_memory_kinds() -> frozenset[str]:
    kinds = set()
    for d in jax.local_devices():
        try:
            kinds.update(m.kind for m in d.addressable_memories())
        except (RuntimeError, NotImplementedError, AttributeError):
            pass
    return frozenset(kinds)


def host_memory_kind() -> str | None:
    """The backend's pinned-host memory kind, or None if unsupported."""
    kinds = _supported_memory_kinds()
    if "pinned_host" in kinds:
        return "pinned_host"
    if "unpinned_host" in kinds:
        return "unpinned_host"
    return None


def _host_axis_degrades() -> bool:
    """True when the HOST/MANAGED space axis collapses to plain device
    placement: no host memory kinds on this backend, or the multi-process
    CPU dev loop — XLA cannot move placement-annotated buffers across a
    multi-controller device order ("Side-effect ops cannot be replicated"
    on the annotate_device_placement custom-call; found by the round-4
    on-chip job.sh matrix when its w=2 managed stencil2d cell died).
    DEVICE is host RAM on CPU anyway; the axis is real on TPU."""
    if host_memory_kind() is None:
        return True
    return (
        jax.process_count() > 1
        and jax.local_devices()[0].platform == "cpu"
    )


def _warn_degraded(context: str) -> None:
    """One-line degrade note (only when the backend HAS host kinds — on
    plain CPU the axis never existed and a warning would be noise)."""
    if host_memory_kind() is not None:
        import warnings

        warnings.warn(
            f"{context}-space placement degraded to plain device "
            "placement on the multi-process CPU backend",
            stacklevel=3,
        )


def host_sharding(sharding, context: str = "host/managed"):
    """Retarget ``sharding`` at the host memory kind for HOST/MANAGED
    placement, or return it UNCHANGED (with a one-line note) when the
    space axis degrades (:func:`_host_axis_degrades`) — the single choke
    point for the retarget, so drivers cannot bypass the multi-process
    guard (the round-4 matrix failure did exactly that)."""
    if _host_axis_degrades():
        _warn_degraded(context)
        return sharding
    return sharding.with_memory_kind(host_memory_kind())


def place(x, space: Space | str = Space.DEVICE, sharding=None):
    """Place an array in the requested space (≅ gt::copy into a spaced tensor).

    ``sharding`` may be a `jax.sharding.Sharding`; for HOST/MANAGED it is
    re-targeted at the host memory kind when supported.
    """
    space = Space.parse(space)
    if space is Space.DEVICE:
        return jax.device_put(x, sharding)
    if sharding is None:
        if _host_axis_degrades():
            # keep the array's placement untouched (committing it to
            # local device 0 would break already-sharded inputs in a
            # multi-process world), but still emit the degrade note
            _warn_degraded(space.value)
            return jax.device_put(x, None)
        sharding = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
    # single choke point for the retarget AND the degrade note — every
    # HOST/MANAGED placement passes through host_sharding
    return jax.device_put(x, host_sharding(sharding, context=space.value))


def ensure_device(x):
    """Promote a host-resident (managed/pinned) array to device memory if
    needed — the managed-space migration-on-first-device-touch rule (TPU has
    no page-migrating unified memory; compiled programs need HBM buffers)."""
    if (
        isinstance(x, jax.Array)
        and getattr(x.sharding, "memory_kind", None) not in (None, "device")
    ):
        return to_device(x)
    return x


def to_device(x, sharding=None):
    """Explicit promotion host→HBM (≅ H2D `gt::copy` / `cudaMemcpy`).

    With no explicit sharding, a committed host-resident array is re-placed
    via its own sharding retargeted at device memory (a bare
    ``device_put(x, None)`` would be a no-op and leave it pinned to host).
    """
    if sharding is None and isinstance(x, jax.Array):
        sharding = x.sharding
    if sharding is not None and getattr(sharding, "memory_kind", None) != "device":
        try:
            sharding = sharding.with_memory_kind("device")
        except (ValueError, NotImplementedError):
            pass  # backend without memory kinds (plain CPU): placement is moot
    return jax.device_put(x, sharding)


def meminfo(x) -> str:
    """Introspect actual placement (≅ MEMINFO/PTRINFO, cuda_error.h:66-136)."""
    if not isinstance(x, jax.Array):
        return f"host(python:{type(x).__name__})"
    shards = x.addressable_shards
    kinds = sorted({s.data.sharding.memory_kind or "device" for s in shards})
    devs = sorted({s.device.id for s in shards})
    return (
        f"kind={','.join(kinds)} devices={devs} "
        f"nbytes={x.nbytes} dtype={x.dtype} shape={tuple(x.shape)}"
    )


def nbytes_report(*arrays) -> str:
    """Rank-0 style allocation report (≅ cudaMemGetInfo print,
    mpi_daxpy_nvtx.cc:201-205, and the device-bytes estimate,
    mpi_stencil2d_sycl.cc:454-465)."""
    total = sum(
        a.nbytes if hasattr(a, "nbytes") else np.asarray(a).nbytes
        for a in arrays
    )
    return f"allocated {len(arrays)} arrays, {total / 2**20:.1f} MiB total"
