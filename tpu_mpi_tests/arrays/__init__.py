"""Array/memory abstraction layer.

TPU-native replacement for the reference's L1 (SURVEY.md §1): gtensor spaces
(device/managed/host), SYCL USM, and raw CUDA allocation become JAX memory
kinds + explicit placement, and the ghost-cell/index arithmetic scattered
through the reference drivers becomes :mod:`tpu_mpi_tests.arrays.domain`.
"""

from tpu_mpi_tests.arrays.spaces import Space, place  # noqa: F401
from tpu_mpi_tests.arrays.domain import Domain1D, Domain2D  # noqa: F401
