"""Fixed-cost Pallas collective tier: one-shot in-kernel collectives for
decode-shape payloads + fused-RDMA ring attention (ISSUE 19).

The reference suite measures exactly the regime where per-op FIXED costs
dominate — tiny ``MPI_Allreduce``/halo payloads on the GENE pattern
(``mpi_stencil2d_gt.cc:574-649``) — and the DECODE pillar reports µs/op
for the same reason: at decode shapes the wire time is nanoseconds while
every XLA dispatch costs microseconds. The ring kernels in
``pallas_kernels.py`` are BANDWIDTH-optimal (2(w−1)/w·n bytes moved) but
pay w−1 (allgather) or 2(w−1) (allreduce) dependent hops; this module
trades bytes for hops:

* :func:`oneshot_allgather_pallas` / :func:`oneshot_allreduce_pallas` —
  ONE ``pallas_call`` in which every rank fires w−1 async remote copies
  of its whole shard directly into every peer's arrival buffer, waits
  the semaphores, and combines arrivals locally. One hop, one launch,
  w−1 · n bytes per rank — the latency-optimal schedule (the
  "one-shot"/direct allreduce of NCCL/MSCCL small-message protocols),
  wins exactly where the DECODE pillar lives and loses at bandwidth
  scale. The sweeper prices the crossover per payload
  (``coll_variant/*``, drivers/collbench.py).
* :func:`fused_ring_attention_pallas` — the PR-15 fused-RDMA pattern
  applied to ring attention: all w ring steps inside one kernel, the
  K/V rotation an in-kernel async remote copy overlapped with the block
  matmul, double-buffered arrival slots with the reduce-scatter's
  receiver-credit handshake. Replaces w ``ppermute`` dispatches + w
  kernel launches with ONE launch (knob ``ring/tier``, comm/ring.py).

Determinism contract: the one-shot allreduce combines arrival slots in
ascending source-rank order through VMEM tiles, so its sum is BITWISE
equal to a sequential left fold over rank shards — gated against the
XLA tier in tests/test_collectives.py. The fused attention kernel reuses
``online_softmax_update`` and the ``_qk/_pv_operands`` precision helpers
from the flash tier, so it can only differ by reassociation (err-norm
gate). Synchronization honesty: every kernel keeps its barrier/handshake
ENABLED under the simulated multi-device interpreter and carries an
``unsafe_*`` negative control that races detectably
(tests/test_ring_sync.py vector-clock contract, PR 15).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_mpi_tests.compat import axis_size, tpu_compiler_params
from tpu_mpi_tests.kernels.pallas_kernels import (
    _VMEM_BUDGET_BYTES,
    _auto_interpret,
    _fit_divisor,
    _pv_operands,
    _qk_operands,
    _serial_interpret,
    _wants_true_f32,
)


def _sublane(dtype) -> int:
    """Sublane tile height for ``dtype`` (8 f32/f64, 16 bf16, 32 int8)."""
    return max(8, 8 * 4 // jnp.dtype(dtype).itemsize)


# ---------------------------------------------------------------------------
# One-shot (single-hop) collectives
# ---------------------------------------------------------------------------


def _oneshot_kernel(x_ref, out_ref, comm_ref, acc_a, acc_b,
                    copy_sem, copy_sem2, send_sem, recv_sem,
                    *, axis_name, w, tile_rows, use_barrier,
                    unsafe_no_recv_wait, op):
    """One-shot collective: every rank DMAs its WHOLE shard into slot
    ``my`` of every peer's ``comm_ref`` in a single burst, then combines
    the w arrivals locally. Latency-optimal: one dependent hop instead
    of the ring's w−1 (gather) / 2(w−1) (allreduce).

    Slot safety needs no per-step semaphores or credit handshake: each
    of the w comm slots is written by exactly ONE DMA in the whole
    program (slot r by rank r's single copy), so a counting
    ``recv_sem`` wait for all w−1 arrivals cannot be satisfied early by
    a same-slot successor — there is none. The entry barrier is
    all-to-all (w−1 signals/waits, not the ring kernels' ±1
    neighborhood): rank p's DMA lands in MY buffer, so MY buffer must
    exist-and-be-quiet before ANY peer starts, not just my neighbors.

    ``unsafe_no_recv_wait`` (negative control, tests/test_ring_sync.py)
    skips the arrival wait: the local combine then reads comm slots
    concurrently with the incoming remote writes — an in-kernel RAW
    race the vector-clock interpreter detects.

    ``op``: ``"gather"`` copies the assembled ``comm_ref`` to
    ``out_ref``; ``"sum"`` folds the slots in ASCENDING source-rank
    order through VMEM tiles (``acc_a``/``acc_b``) — the fixed sum
    order that makes the result bitwise-reproducible and
    world-placement independent (same combine order on every rank,
    unlike a ring whose partial-sum order is rank-relative)."""
    my = lax.axis_index(axis_name)
    n = x_ref.shape[0]

    if use_barrier:
        barrier = pltpu.get_barrier_semaphore()
        for k in range(1, w):
            peer = lax.rem(my + jnp.int32(k), jnp.int32(w))
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=peer,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
        pltpu.semaphore_wait(barrier, w - 1)

    # own shard into own slot (local DMA, overlaps the remote burst)
    own = pltpu.make_async_copy(
        x_ref, comm_ref.at[pl.ds(my * n, n)], copy_sem
    )
    own.start()

    # the one-shot burst: full shard to slot `my` of every peer, all
    # in flight at once. Shared counting send/recv semaphores are safe
    # (see docstring); iteration k is the uniform shift-by-k
    # permutation, which is also what lets the serialized interpreter
    # emulate each iteration as one collective.
    handles = []
    for k in range(1, w):
        peer = lax.rem(my + jnp.int32(k), jnp.int32(w))
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref,
            dst_ref=comm_ref.at[pl.ds(my * n, n)],
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=peer,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        handles.append(rdma)
    own.wait()
    for h in handles:
        h.wait_send()
    if not unsafe_no_recv_wait:
        for h in handles:
            h.wait_recv()

    if op == "gather":
        # one local copy comm → out. Deliberately NOT aliased away:
        # reading the arrival buffer here is what makes the skipped
        # recv-wait control an in-kernel RAW race instead of a
        # silently-correct no-op.
        cp = pltpu.make_async_copy(comm_ref, out_ref, copy_sem)
        cp.start()
        cp.wait()
        return

    # allreduce: ascending-src-order fold through VMEM tiles
    for j in range(n // tile_rows):
        ca = pltpu.make_async_copy(
            comm_ref.at[pl.ds(j * tile_rows, tile_rows)], acc_a, copy_sem
        )
        ca.start()
        ca.wait()
        for s in range(1, w):
            cb = pltpu.make_async_copy(
                comm_ref.at[pl.ds(s * n + j * tile_rows, tile_rows)],
                acc_b, copy_sem2,
            )
            cb.start()
            cb.wait()
            acc_a[:] = acc_a[:] + acc_b[:]
        cw = pltpu.make_async_copy(
            acc_a, out_ref.at[pl.ds(j * tile_rows, tile_rows)], copy_sem
        )
        cw.start()
        cw.wait()


def _oneshot_call(x, *, axis_name, op, collective_id, interpret,
                  unsafe_no_recv_wait, fn_name):
    """Shared wrapper for the two one-shot ops: pad-to-tile, 1-D lane
    fold, VMEM tile fit, and the ``pallas_call``.

    PAD-TO-TILE, not an alignment floor: the sliced comm-slot DMAs need
    sublane-aligned rows (1-D shards: 128·sublane elements) like the
    ring kernels — but where the ring tier REJECTS misaligned decode
    payloads (its chunking floor also carries a factor w), this tier
    zero-pads the shard up to the tile and slices the result back. The
    one-shot schedule exists for payloads whose wire time is noise
    against the per-hop fixed cost, so shipping a padded lane tile
    costs the same single hop — and the pad rows are zeros folded into
    zeros (sum) or sliced away (gather), never observable."""
    sublane = _sublane(x.dtype)
    w = axis_size(axis_name)
    n = x.shape[0]
    if x.ndim == 1:
        unit = 128 * sublane
        pad = (-n) % unit
        if pad:
            out = _oneshot_call(
                jnp.pad(x, (0, pad)), axis_name=axis_name, op=op,
                collective_id=collective_id, interpret=interpret,
                unsafe_no_recv_wait=unsafe_no_recv_wait, fn_name=fn_name,
            )
            if op == "gather":
                return out.reshape(w, -1)[:, :n].reshape(-1)
            return out[:n]
        # fold to 128-lane rows (Mosaic sliced DMA needs full lane tiles)
        out = _oneshot_call(
            x.reshape(-1, 128), axis_name=axis_name, op=op,
            collective_id=collective_id, interpret=interpret,
            unsafe_no_recv_wait=unsafe_no_recv_wait, fn_name=fn_name,
        )
        return out.reshape(-1)
    pad = (-n) % sublane
    if pad:
        out = _oneshot_call(
            jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1)),
            axis_name=axis_name, op=op, collective_id=collective_id,
            interpret=interpret,
            unsafe_no_recv_wait=unsafe_no_recv_wait, fn_name=fn_name,
        )
        if op == "gather":
            return out.reshape((w, -1) + x.shape[1:])[:, :n].reshape(
                (w * n,) + x.shape[1:]
            )
        return out[:n]
    interp = _auto_interpret(interpret)
    row_bytes = jnp.dtype(x.dtype).itemsize * math.prod(x.shape[1:])
    # accumulate tiles: sublane-aligned divisor of n, two tiles within
    # the VMEM budget (decode payloads fit whole; the fit only engages
    # when someone points the one-shot tier at bandwidth-scale shards)
    max_units = max(1, _VMEM_BUDGET_BYTES // max(1, 2 * row_bytes * sublane))
    tile_rows = sublane * _fit_divisor(n // sublane, max_units)
    out_rows = w * n if op == "gather" else n
    out_struct = jax.ShapeDtypeStruct((out_rows, *x.shape[1:]), x.dtype)
    comm_struct = jax.ShapeDtypeStruct((w * n, *x.shape[1:]), x.dtype)
    out, _ = pl.pallas_call(
        functools.partial(
            _oneshot_kernel,
            axis_name=axis_name,
            w=w,
            tile_rows=tile_rows,
            use_barrier=not _serial_interpret(interp),
            unsafe_no_recv_wait=unsafe_no_recv_wait,
            op=op,
        ),
        out_shape=(out_struct, comm_struct),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        # comm_ref is an OUT ref (not scratch): remote DMAs land in it,
        # so it must be addressable by peers — and the serialized
        # interpreter can only emulate remote copies between
        # program-visible buffers (the reduce-scatter's comm_ref idiom)
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((tile_rows, *x.shape[1:]), x.dtype),
            pltpu.VMEM((tile_rows, *x.shape[1:]), x.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(x)
    return out


def oneshot_allgather_pallas(
    x,
    *,
    axis_name: str,
    collective_id: int = 13,
    interpret: bool | None = None,
    unsafe_no_recv_wait: bool = False,
):
    """One-shot all-gather along axis 0: every rank remote-copies its
    whole (n, m) shard directly into slot ``r`` of every peer's arrival
    buffer in a single launch — one dependent hop vs the ring tier's
    w−1 (:func:`~tpu_mpi_tests.kernels.pallas_kernels.ring_allgather_pallas`).
    Call *inside* ``shard_map``; returns the (w·n, m) gathered array.

    Moves (w−1)·n rows per rank instead of the ring's same total spread
    over w−1 DEPENDENT steps: at decode payloads where each hop is pure
    fixed cost, total time collapses from (w−1)·t_hop to ~t_hop. The
    crossover against the bandwidth-optimal ring is priced per payload
    by the ``coll_variant/*`` sweep (drivers/collbench.py).

    Alignment: none required — misaligned shards are zero-padded up to
    the DMA tile and sliced back (see ``_oneshot_call``); at the
    latency-bound payloads this tier targets, a padded lane tile costs
    the same single hop. The ring tier instead REJECTS payloads below
    its w·128·sublane chunking floor — which is exactly the decode
    range."""
    return _oneshot_call(
        x, axis_name=axis_name, op="gather",
        collective_id=collective_id, interpret=interpret,
        unsafe_no_recv_wait=unsafe_no_recv_wait,
        fn_name="oneshot_allgather_pallas",
    )


def oneshot_allreduce_pallas(
    x,
    *,
    axis_name: str,
    collective_id: int = 14,
    interpret: bool | None = None,
    unsafe_no_recv_wait: bool = False,
):
    """One-shot allreduce(sum): the one-hop gather burst of
    :func:`oneshot_allgather_pallas`, then each rank folds the w arrival
    slots locally in ASCENDING source-rank order through VMEM tiles.
    Call *inside* ``shard_map``; every rank returns the full (n, m)
    elementwise sum.

    vs the ring allreduce's 2(w−1) dependent hops
    (reduce-scatter + allgather): one hop, at the cost of w−1 full
    shards on the wire per rank and the full w-term fold on every rank
    — the classic latency/bandwidth trade the sweeper prices.

    Determinism: the ascending-src fold makes the sum bitwise equal to
    ``functools.reduce(np.add, [shard_0, …, shard_{w-1}])`` on every
    rank — a FIXED, rank-independent order (the ring tier's partial-sum
    order is rank-relative), gated in tests/test_collectives.py."""
    return _oneshot_call(
        x, axis_name=axis_name, op="sum",
        collective_id=collective_id, interpret=interpret,
        unsafe_no_recv_wait=unsafe_no_recv_wait,
        fn_name="oneshot_allreduce_pallas",
    )


# ---------------------------------------------------------------------------
# Fused-RDMA ring attention
# ---------------------------------------------------------------------------


def _fused_live_bytes(lq: int, lk: int, d: int, dtype) -> int:
    """VMEM live model for the fused ring-attention kernel: the staged
    q/k/v tiles + the result tile, the f32 carry (m, l, acc), the f32
    scores block and its dtype-cast probability copy, and (for sub-f32
    inputs, which the HIGHEST-precision default upcasts in-kernel) the
    f32 operand copies — the ``_fit_flash_tiles`` live model with the
    whole local block as the single tile."""
    item = jnp.dtype(dtype).itemsize
    return (
        (2 * lq + 2 * lk) * d * item        # q_buf, o_buf, k_buf, v_buf
        + 2 * lq * 4                        # m, l carries (f32)
        + lq * d * 4                        # acc carry (f32)
        + lq * lk * (4 + item)              # scores f32 + p dtype copy
        + ((lq + 2 * lk) * d * 4 if item < 4 else 0)
    )


def fused_ring_feasible(lq: int, lk: int, d: int, dtype) -> bool:
    """Can the fused one-launch ring-attention kernel run this geometry?
    True when the whole local block fits the VMEM live model AND the
    K/V block height is sublane-aligned (the arrival-slot DMA floor).
    Drivers consult this to decline the fused tier with a NOTE instead
    of tripping the kernel's ValueError (bench.py stencil-tier idiom);
    the crossover being SMALL geometries is by design — the fused tier
    is the fixed-cost end of the spectrum, the host-pipelined tier
    (``ring/pipeline_depth``) remains the bandwidth end."""
    return (
        lk % _sublane(dtype) == 0
        and _fused_live_bytes(lq, lk, d, dtype) <= _VMEM_BUDGET_BYTES
    )


def _fused_ring_attention_kernel(
    q_ref, k_ref, v_ref, out_ref, comm_ref,
    q_buf, k_buf, v_buf, o_buf,
    copy_sem, copy_sem2, send_sem, recv_sem, ready_sem,
    *, axis_name, w, lk, scale, causal, stripe, precision,
    use_barrier, use_handshake, credits,
):
    """All w ring-attention steps in ONE kernel: step ``s`` forwards the
    current K/V block to the right neighbor via async remote copy and
    runs the flash fold on it WHILE the DMA flies — the PR-15 fused-RDMA
    overlap, with the launch/dispatch cost paid once instead of per
    step.

    ``comm_ref`` holds two parity slots of (K rows ‖ V rows); step ``s``
    consumes slot ``s % 2`` and receives into slot ``(s+1) % 2``. Slot
    safety is the reduce-scatter's credits=2 contract verbatim: sends
    ``s ≥ credits`` wait one receiver credit on ``ready_sem``, consumers
    signal left after retiring slot ``s ≤ w−2−credits``, and PER-PARITY
    ``recv_sem`` indices keep an anonymous counting wait from being
    satisfied by the ``s+1`` arrival while slot ``s % 2`` is still being
    written (the round-4 RAW hazard class). ``unsafe_no_credits``
    (negative control) drops the credit waits/signals: writes ``s`` and
    ``s+2`` then share a slot with nothing separating them — the
    vector-clock interpreter detects the overwrite race at w ≥ 4.

    The fold itself reuses ``online_softmax_update`` and the
    ``_qk/_pv_operands`` precision helpers from the flash tier — same
    recurrence, same masking, so the tiers differ only by
    reassociation. Causal masking is a full-width ``where`` in global
    positions (contiguous: ``r·L+i``; striped: ``i·w+r``): fused-tier
    geometries are decode/latency scale, where the three-regime skip
    machinery's bookkeeping outweighs the masked FLOPs it saves."""
    from tpu_mpi_tests.comm.ring import online_softmax_update

    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, jnp.int32(w))
    left = lax.rem(my - 1 + jnp.int32(w), jnp.int32(w))

    if use_barrier:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    # stage q once; seed arrival parity 0 with the local K/V block
    qc = pltpu.make_async_copy(q_ref, q_buf, copy_sem)
    qc.start()
    if w > 1:
        sk = pltpu.make_async_copy(
            k_ref, comm_ref.at[pl.ds(0, lk)], copy_sem2
        )
        sk.start()
        sk.wait()
        sv = pltpu.make_async_copy(
            v_ref, comm_ref.at[pl.ds(lk, lk)], copy_sem2
        )
        sv.start()
        sv.wait()
    qc.wait()

    lq, d = q_buf.shape
    q = q_buf[:]
    if _wants_true_f32(precision) and q.dtype != jnp.float32:
        q = q.astype(jnp.float32)
    if causal:
        if stripe:  # striped position of row i on shard p: i·w + p
            q_pos = my + jnp.int32(w) * lax.broadcasted_iota(
                jnp.int32, (lq, 1), 0
            )
            k_iota = jnp.int32(w) * lax.broadcasted_iota(
                jnp.int32, (1, lk), 1
            )
        else:
            q_pos = my * lq + lax.broadcasted_iota(jnp.int32, (lq, 1), 0)
            k_iota = lax.broadcasted_iota(jnp.int32, (1, lk), 1)

    m = jnp.full((lq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((lq, 1), jnp.float32)
    acc = jnp.zeros((lq, d), jnp.float32)

    for s in range(w):
        cur, nxt = s % 2, (s + 1) % 2
        rdma = None
        if s < w - 1:
            if use_handshake and s >= credits:
                # right retired my payload s − credits: a slot is free
                pltpu.semaphore_wait(ready_sem, 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_ref.at[pl.ds(cur * 2 * lk, 2 * lk)],
                dst_ref=comm_ref.at[pl.ds(nxt * 2 * lk, 2 * lk)],
                send_sem=send_sem,
                recv_sem=recv_sem.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()  # flies under the fold below

        # stage this step's K/V block into VMEM (ANY-space arrival
        # slots cannot feed the MXU directly); step 0 reads the inputs
        # straight, skipping the comm round trip
        if s == 0:
            ck = pltpu.make_async_copy(k_ref, k_buf, copy_sem)
            cv = pltpu.make_async_copy(v_ref, v_buf, copy_sem2)
        else:
            ck = pltpu.make_async_copy(
                comm_ref.at[pl.ds(cur * 2 * lk, lk)], k_buf, copy_sem
            )
            cv = pltpu.make_async_copy(
                comm_ref.at[pl.ds(cur * 2 * lk + lk, lk)], v_buf,
                copy_sem2,
            )
        ck.start()
        cv.start()
        ck.wait()
        cv.wait()

        # flash fold of the block from source rank (my − s) mod w
        kb, vb = k_buf[:], v_buf[:]
        scores = lax.dot_general(
            *_qk_operands(q, kb, precision), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        ) * scale
        if causal:
            src = lax.rem(my - jnp.int32(s) + jnp.int32(w), jnp.int32(w))
            k_pos = (src if stripe else src * lk) + k_iota
            scores = jnp.where(q_pos >= k_pos, scores, -jnp.inf)
        m, l, p, corr = online_softmax_update(m, l, scores, keepdims=True)
        acc = acc * corr + lax.dot_general(
            *_pv_operands(p, vb, precision), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )

        if rdma is not None:
            # own send done + next block arrived (parity recv wait)
            rdma.wait()
            if use_handshake and s <= w - 2 - credits:
                # slot `cur` is retired (staged to VMEM above, send
                # landed): release left's send s + credits
                pltpu.semaphore_signal(
                    ready_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )

    o_buf[:] = (acc / l).astype(o_buf.dtype)
    oc = pltpu.make_async_copy(o_buf, out_ref, copy_sem)
    oc.start()
    oc.wait()


def fused_ring_attention_pallas(
    q,
    k,
    v,
    *,
    axis_name: str,
    scale: float | None = None,
    causal: bool = False,
    stripe: bool = False,
    precision=lax.Precision.HIGHEST,
    interpret: bool | None = None,
    collective_id: int = 15,
    unsafe_no_credits: bool = False,
):
    """One-launch fused-RDMA ring attention for one shard (call *inside*
    ``shard_map``): all w ring steps in a single ``pallas_call``, the
    K/V rotation an in-kernel async remote copy overlapped with the
    block matmul — the fixed-cost tier of the ring-attention pair (knob
    ``ring/tier``, comm/ring.py), replacing w ``ppermute`` dispatches +
    w kernel launches with one launch.

    ``q``/``k``/``v``: this rank's (L_local, d) blocks; same semantics,
    masking, and precision contract as
    :func:`~tpu_mpi_tests.comm.ring.ring_attention` (striped layout
    included) — the tiers are interchangeable per test, differing only
    by reassociation.

    The whole local block must fit the VMEM live model
    (:func:`fused_ring_feasible`): the fused tier deliberately has NO
    streaming fallback — where it does not fit, the host-pipelined tier
    is the right tool and callers decline with a NOTE instead
    (drivers/attnbench.py)."""
    if q.ndim != 2 or k.shape != v.shape or q.shape[-1] != k.shape[-1]:
        raise ValueError(
            f"fused_ring_attention_pallas expects (L, d) blocks with "
            f"matching K/V, got q={q.shape} k={k.shape} v={v.shape}"
        )
    if stripe and not causal:
        raise ValueError(
            "stripe=True only makes sense for causal ring attention "
            "(non-causal work is already balanced)"
        )
    lq, d = q.shape
    lk = k.shape[0]
    sublane = _sublane(k.dtype)
    if lk % sublane != 0:
        raise ValueError(
            f"fused_ring_attention_pallas needs K/V rows % {sublane} "
            f"== 0 for {jnp.dtype(k.dtype).name} (arrival-slot DMA "
            f"tile), got {lk}"
        )
    if not fused_ring_feasible(lq, lk, d, q.dtype):
        raise ValueError(
            f"fused ring attention block does not fit VMEM: lq={lq} "
            f"lk={lk} d={d} {jnp.dtype(q.dtype).name} needs "
            f"{_fused_live_bytes(lq, lk, d, q.dtype) / 2**20:.1f} MiB "
            f"vs the ~{_VMEM_BUDGET_BYTES / 2**20:.0f} MiB budget; use "
            f"the pipelined tier (ring/tier=pipelined) at this geometry"
        )
    if scale is None:
        scale = 1.0 / (d**0.5)
    interp = _auto_interpret(interpret)
    w = axis_size(axis_name)
    out_struct = jax.ShapeDtypeStruct((lq, d), q.dtype)
    comm_struct = jax.ShapeDtypeStruct((2 * 2 * lk, d), k.dtype)
    out, _ = pl.pallas_call(
        functools.partial(
            _fused_ring_attention_kernel,
            axis_name=axis_name,
            w=w,
            lk=lk,
            scale=float(scale),
            causal=causal,
            stripe=stripe,
            precision=precision,
            use_barrier=not _serial_interpret(interp),
            use_handshake=(
                not _serial_interpret(interp) and not unsafe_no_credits
            ),
            credits=2,
        ),
        out_shape=(out_struct, comm_struct),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((lq, d), q.dtype),
            pltpu.VMEM((lk, d), k.dtype),
            pltpu.VMEM((lk, d), v.dtype),
            pltpu.VMEM((lq, d), q.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(q, k, v)
    return out
