"""DAXPY: y ← a·x + y.

TPU-native replacement for ``cublasDaxpy`` (``daxpy.cu:72-73``,
``mpi_daxpy_gt.cc:81``). The XLA version is a fused elementwise op — on TPU
this is HBM-bandwidth bound (3 array accesses per element), exactly like the
cuBLAS call on V100, so GB/s is the comparable metric (BASELINE.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def daxpy(a, x, y):
    """y ← a·x + y. ``a`` may be a python scalar or 0-d array."""
    return a * x + y


def daxpy_bytes(n: int, dtype=jnp.float32) -> int:
    """Memory traffic of one daxpy: read x, read y, write y."""
    return 3 * n * jnp.dtype(dtype).itemsize


def init_xy(n: int, dtype=jnp.float32):
    """Reference initialization x=i+1, y=-(i+1) (``daxpy.cu:56-59``), giving
    y ← 2x+y = i+1 and the exact checksum n(n+1)/2."""
    i = jnp.arange(1, n + 1, dtype=dtype)
    return i, -i


def expected_checksum(n: int) -> float:
    return n * (n + 1) / 2
