"""DAXPY: y ← a·x + y.

TPU-native replacement for ``cublasDaxpy`` (``daxpy.cu:72-73``,
``mpi_daxpy_gt.cc:81``). The XLA version is a fused elementwise op — on TPU
this is HBM-bandwidth bound (3 array accesses per element), exactly like the
cuBLAS call on V100, so GB/s is the comparable metric (BASELINE.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def daxpy(a, x, y):
    """y ← a·x + y. ``a`` may be a python scalar or 0-d array."""
    return a * x + y


def daxpy_bytes(n: int, dtype=jnp.float32) -> int:
    """Memory traffic of one daxpy: read x, read y, write y."""
    return 3 * n * jnp.dtype(dtype).itemsize


def init_xy(n: int, dtype=jnp.float32):
    """Reference initialization x=i+1, y=-(i+1) (``daxpy.cu:56-59``), giving
    y ← 2x+y = i+1 and the exact checksum n(n+1)/2."""
    i = jnp.arange(1, n + 1, dtype=dtype)
    return i, -i


def init_xy_np(n: int, dtype=np.float64):
    """Host-side variant of :func:`init_xy` (``mpi_daxpy.cc:94-97``)."""
    i = np.arange(1, n + 1, dtype=np.float64).astype(dtype)
    return i, -i


def init_xy_scaled_np(n: int, dtype=np.float64):
    """Flagship init x=(i+1)/n, y=-x (``mpi_daxpy_nvtx.cc:207-217``); with
    a=2 the result is y=x and the local checksum is (n+1)/2."""
    x = (np.arange(1, n + 1, dtype=np.float64) / n).astype(dtype)
    return x, -x


def init_xy_scaled_jax(n: int, dtype):
    """Device-side (traceable) twin of :func:`init_xy_scaled_np` — at 48Mi
    elements/node the host-init + transfer path is tunnel-bound; the
    pattern is analytic, so shards can compute it on chip."""
    x = jnp.arange(1, n + 1, dtype=dtype) / jnp.asarray(n, dtype)
    return x, -x


def expected_checksum(n: int) -> float:
    return n * (n + 1) / 2


def expected_checksum_scaled(n: int) -> float:
    return (n + 1) / 2
