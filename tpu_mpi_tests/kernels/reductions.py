"""Reductions: sum-of-squares error norms and axis sums.

TPU-native replacement for ``gt::sum_squares`` (``mpi_stencil_gt.cc:222``),
``gt::sum_axis_to`` (``mpi_stencil2d_gt.cc:611,620``), and the SYCL
``diff_norm`` reduction kernel (``mpi_stencil2d_sycl.cc:165-181``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def sum_squares(x):
    return jnp.sum(jnp.square(x))


@jax.jit
def err_norm(numeric, actual):
    """sqrt(Σ(numeric − actual)²) — the stencil correctness gate
    (≅ ``diff_norm`` + sqrt at ``mpi_stencil_gt.cc:222``)."""
    return jnp.sqrt(sum_squares(numeric - actual))


def sum_axis(x, axis: int):
    """Reduce one axis to a vector (≅ ``gt::sum_axis_to``)."""
    return jnp.sum(x, axis=axis)


sum_axis_jit = jax.jit(sum_axis, static_argnames=("axis",))
