"""Hand-written Pallas TPU kernels: the "raw CUDA/SYCL" tier.

The reference carries every kernel twice: a portable expression-template
version (gtensor, ``mpi_stencil2d_gt.cc``) and a hand-written one (SYCL
``parallel_for``, ``mpi_stencil2d_sycl.cc:53-116``; cuBLAS call,
``daxpy.cu:72-73``). This module is the hand-written tier for TPU — explicit
VMEM staging, DMA pipelines, and tile-aligned grids — mirroring:

* ``daxpy_pallas``       ≅ ``cublasDaxpy`` (``daxpy.cu:72-73``)
* ``stencil2d_pallas``   ≅ ``stencil2d_1d_5`` SYCL kernel
  (``mpi_stencil2d_sycl.cc:53-75``): grid of full-extent strips along the
  non-derivative dim, each strip staged in VMEM where the 5 shifted reads
  are VPU shifts. This is the explicit form of what XLA fuses automatically
  (kernels/stencil.py) — the A/B pair the reference keeps on purpose.
* ``pack_edges_pallas`` / ``unpack_ghosts_pallas`` ≅ ``buf_from_view`` /
  ``buf_to_view`` staging kernels (``mpi_stencil2d_sycl.cc:82-116``).

All kernels run compiled on TPU and in interpreter mode elsewhere
(``interpret=None`` auto-selects), so the same tests cover both.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_mpi_tests.compat import axis_size, tpu_compiler_params
from tpu_mpi_tests.kernels.stencil import N_BND, STENCIL5


def _auto_interpret(interpret):
    """Resolve an ``interpret`` argument: ``None`` → interpret off-TPU;
    a bool or a :class:`pltpu.InterpretParams` passes through unchanged.

    ``InterpretParams`` selects the SIMULATED MULTI-DEVICE interpreter
    (one thread per simulated device, shared-memory semaphores, simulated
    remote DMA, optional vector-clock race detection) — unlike the plain
    ``True`` interpreter, which serializes devices and emulates remote
    DMA with XLA collectives. The ring kernels keep their hardware
    synchronization (entry barrier, receiver-backpressure handshake)
    ENABLED under ``InterpretParams``: those lines then actually execute
    concurrently, giving CI coverage of the sync logic itself."""
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _serial_interpret(interp) -> bool:
    """True only for the plain bool interpreter (devices serialized,
    remote signals unimplemented) — the mode in which hardware-style
    synchronization must be compiled out. False on hardware AND under the
    threaded :class:`pltpu.InterpretParams` simulator, where the real
    barrier/handshake path both works and is the point."""
    return isinstance(interp, bool) and interp


# ---------------------------------------------------------------------------
# DAXPY
# ---------------------------------------------------------------------------


def _daxpy_kernel(a_ref, x_ref, y_ref, out_ref):
    out_ref[:] = a_ref[0] * x_ref[:] + y_ref[:]


def _stream_block_rows(itemsize: int, n_bufs: int) -> int:
    """Largest power-of-two block for an n_bufs-buffer streaming kernel that
    keeps double-buffered tiles within ~12 MB of the ~16 MB VMEM: big tiles
    are what saturate HBM (682 GB/s at 4096×128 f32 vs 620 at 512×128 on
    v5e; 8192×128 OOMs)."""
    budget = 12 * 2**20
    rows = budget // (n_bufs * 2 * 128 * itemsize)
    return 1 << (rows.bit_length() - 1)


@functools.partial(
    jax.jit, static_argnames=("block_rows", "interpret", "inplace")
)
def daxpy_pallas(a, x, y, block_rows: int | None = None,
                 interpret: bool | None = None, inplace: bool = False):
    """y ← a·x + y on 1-D arrays (≅ ``cublasDaxpy``).

    The array is viewed as (rows, 128) lanes and processed in
    ``block_rows``-row VMEM tiles (default: dtype-dependent maximum, 4096
    for f32); n must be a multiple of 128 (driver sizes are powers of two,
    like the reference's 48Mi-per-node sizing).

    ``inplace=True`` aliases the output onto ``y`` — cuBLAS's actual
    in-place semantics, and REQUIRED for chained loops: a measured A/B
    (BASELINE.md; reproduced by ``tpu/microbench.py daxpy`` chained rows)
    shows the non-aliased form collapses to 398 GB/s inside a
    ``fori_loop`` (per-iteration output-buffer churn) while the aliased
    form holds the standalone 685 GB/s. The alias pays off only when the
    CALLER owns the buffer — inside an outer jit that carries ``y`` (e.g.
    a ``fori_loop`` body) or a top-level call whose outer jit donates it;
    called standalone on a live entry array, XLA must insert a defensive
    copy (entry params are immutable), costing a 4th pass.
    """
    n = x.shape[0]
    if n % 128 != 0:
        raise ValueError(f"daxpy_pallas needs n % 128 == 0, got {n}")
    rows = n // 128
    if block_rows is None:
        block_rows = _stream_block_rows(jnp.dtype(x.dtype).itemsize, 3)
    block_rows = min(block_rows, rows)
    x2 = x.reshape(rows, 128)
    y2 = y.reshape(rows, 128)
    a_arr = jnp.asarray(a, x.dtype).reshape(1)
    grid = (pl.cdiv(rows, block_rows),)
    out = pl.pallas_call(
        _daxpy_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 128), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        input_output_aliases=({2: 0} if inplace else {}),
        interpret=_auto_interpret(interpret),
    )(a_arr, x2, y2)
    return out.reshape(n)


def _scale_kernel(a_ref, x_ref, out_ref):
    out_ref[:] = a_ref[0] * x_ref[:]


@functools.partial(
    jax.jit, static_argnames=("block_rows", "interpret", "inplace")
)
def stream_scale_pallas(a, x, block_rows: int | None = None,
                        interpret: bool | None = None,
                        inplace: bool = False):
    """out ← a·x: the minimal 2-pass (read + write) HBM stream.

    This is the ceiling probe's second point: with daxpy (3 passes) it gives
    two (bytes, seconds) samples whose linear fit separates true stream
    bandwidth from the fixed per-kernel launch overhead — the roofline model
    BASELINE.md uses (a raw small-op rate under-reports the ceiling because
    the launch overhead is charged to too few bytes). ``inplace`` aliases
    the output onto ``x`` (required for chained loops — the daxpy_pallas
    aliasing lesson)."""
    n = x.shape[0]
    if n % 128 != 0:
        raise ValueError(f"stream_scale_pallas needs n % 128 == 0, got {n}")
    rows = n // 128
    if block_rows is None:
        block_rows = _stream_block_rows(jnp.dtype(x.dtype).itemsize, 2)
    block_rows = min(block_rows, rows)
    a_arr = jnp.asarray(a, x.dtype).reshape(1)
    out = pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 128), x.dtype),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        input_output_aliases=({1: 0} if inplace else {}),
        interpret=_auto_interpret(interpret),
    )(a_arr, x.reshape(rows, 128))
    return out.reshape(n)


def _sum3_kernel(w_ref, x_ref, y_ref, out_ref):
    out_ref[:] = w_ref[:] + x_ref[:] + y_ref[:]


@functools.partial(
    jax.jit, static_argnames=("block_rows", "interpret", "inplace")
)
def stream_sum3_pallas(w, x, y, block_rows: int | None = None,
                       interpret: bool | None = None,
                       inplace: bool = False):
    """y ← w + x + y: the 4-stream (3 reads + 1 write) HBM probe.

    Completes the stream-count family {2: scale, 3: daxpy, 4: this} whose
    linear fit t(S) = overhead + S·bytes/BW separates the true per-stream
    HBM bandwidth from fixed launch overhead — the round-3 probe for the
    DAXPY 0.92× structural-gap question (VERDICT r2 weak #4). ``inplace``
    aliases the output onto ``y`` (same contract and chained-loop
    requirement as ``daxpy_pallas``; defaults off like its siblings so a
    standalone call doesn't force a defensive copy)."""
    if not (w.shape == x.shape == y.shape and w.dtype == x.dtype == y.dtype):
        raise ValueError(
            "stream_sum3_pallas needs w/x/y of identical shape and dtype, "
            f"got {w.shape}/{w.dtype}, {x.shape}/{x.dtype}, "
            f"{y.shape}/{y.dtype}"
        )
    # n/dtype derived from y, the alias target when inplace=True
    n = y.shape[0]
    if n % 128 != 0:
        raise ValueError(f"stream_sum3_pallas needs n % 128 == 0, got {n}")
    rows = n // 128
    if block_rows is None:
        block_rows = _stream_block_rows(jnp.dtype(y.dtype).itemsize, 4)
    block_rows = min(block_rows, rows)
    spec = pl.BlockSpec(
        (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _sum3_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 128), x.dtype),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        input_output_aliases=({2: 0} if inplace else {}),
        interpret=_auto_interpret(interpret),
    )(w.reshape(rows, 128), x.reshape(rows, 128), y.reshape(rows, 128))
    return out.reshape(n)


def _vpu_probe_kernel(z_ref, out_ref, *, reps, mix, se):
    z = z_ref[:]

    if mix == "fma":
        # 2 nominal VPU ops/elt/rep (mul + add; one op if the hardware
        # fuses) — the dependent chain pipelines across the block's rows,
        # so this measures elementwise THROUGHPUT, not ALU latency.
        # Constants take the BLOCK dtype (f32 literals would promote a
        # bf16 block to f32 compute and silently measure the wrong mix)
        # and must be FOLD-PROOF in every dtype: 1.0000001 rounds to
        # exactly 1.0 in bf16 and the multiply could be simplified away.
        # a = 1 − 2⁻⁷ is exact in bf16 and f32, and with a < 1 the
        # recurrence converges to the b/(1−a) ≈ 1.3e-8 fixed point —
        # kilorep chains neither overflow nor decay to a foldable zero
        a = jnp.asarray(0.9921875, z.dtype)
        b = jnp.asarray(1e-10, z.dtype)

        def body(_, z):
            return a * z + b
    elif mix == "heat5":
        # the EXACT heat Laplacian step body (_heat_stream0_kernel's
        # per-step update: 4 full-extent concat shifts, the two-axis
        # explicit-Euler expression, the border where-mask) applied to
        # the resident block — ~11 nominal ops/elt/rep plus the shifts.
        # cx = cy = 2⁻⁷: exact in bf16 and f32 (fold-proof, round-4 fma
        # lesson), and a CONTRACTIVE diffusion step — rep chains decay
        # toward the block mean, never overflow
        cx = jnp.asarray(0.0078125, z.dtype)
        cy = jnp.asarray(0.0078125, z.dtype)
        H, W = z.shape
        wi = jax.lax.broadcasted_iota(jnp.int32, z.shape, 0)
        ci = jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
        ok = (wi >= 1) & (wi < H - 1) & (ci >= 1) & (ci < W - 1)

        def body(_, w):
            up = jnp.concatenate([w[1:H], w[H - 1:H]], axis=0)
            down = jnp.concatenate([w[0:1], w[0:H - 1]], axis=0)
            right = jnp.concatenate([w[:, 1:W], w[:, W - 1:W]], axis=1)
            left = jnp.concatenate([w[:, 0:1], w[:, 0:W - 1]], axis=1)
            new = (w + cx * (up + down - 2.0 * w)
                   + cy * (left + right - 2.0 * w))
            return jnp.where(ok, new, w)
    elif mix == "dualdim":
        # the EXACT dual-dim step body (_dual_step_kernel: 4-tap
        # derivative accumulations on BOTH axes from one window read,
        # per-axis scale, TWO row-masked f32 squared-residual
        # reductions) — ~22 nominal ops/elt/rep, each mask's `where`
        # counted as one op (the same convention as dualdim_lean's 14,
        # which counts its single mask). The derivatives fold back into
        # the interior and
        # the residual scalar folds in ``se``-scaled so every output
        # element depends on the whole reduction (nothing dead-codes);
        # tests replicate this recurrence in numpy
        se_c = jnp.asarray(se, z.dtype)
        sx = jnp.asarray(0.0078125, z.dtype)
        sy = jnp.asarray(0.0078125, z.dtype)
        H, W = z.shape
        taps = [(k, c) for k, c in enumerate(STENCIL5.tolist())
                if c != 0.0]

        def body(_, zz):
            accx = None
            for k, c in taps:
                t = c * jax.lax.slice_in_dim(zz, k, k + H - 2 * N_BND,
                                             axis=0)
                accx = t if accx is None else accx + t
            dx = accx * sx                      # (H-2G, W)
            accy = None
            for k, c in taps:
                t = c * jax.lax.slice_in_dim(zz, k, k + W - 2 * N_BND,
                                             axis=1)
                accy = t if accy is None else accy + t
            dy = accy * sy                      # (H, W-2G)
            dxf = dx.astype(jnp.float32)
            dyf = dy.astype(jnp.float32)
            # scalar chain stays f32 end-to-end: bf16 scalar arith.mulf /
            # addf / divf do not legalize on the TPU scalar unit (the
            # round-4 dual-dim kernel finding, re-confirmed here) — the
            # scalar broadcasts to an f32 vector and casts at the fold.
            # The two row-masked reductions mirror the kernel's ragged
            # last-block `where(valid, ...)` pair (review fix: the mix
            # originally omitted them, underpricing the raw kernel's op
            # mix vs the lean variant's single masked reduction); the
            # mask excludes the last row — mixed true/false, fold-proof
            rx = jax.lax.broadcasted_iota(jnp.int32, dxf.shape, 0)
            ry = jax.lax.broadcasted_iota(jnp.int32, dyf.shape, 0)
            zf = jnp.zeros((), jnp.float32)
            r = (jnp.sum(jnp.where(rx < H - 2 * N_BND - 1, dxf * dxf, zf))
                 + jnp.sum(jnp.where(ry < H - 1, dyf * dyf, zf))) / 1024.0
            shift = jnp.asarray(se, jnp.float32) * r
            zx = jnp.concatenate(
                [
                    jax.lax.slice_in_dim(zz, 0, N_BND, axis=0),
                    jax.lax.slice_in_dim(zz, N_BND, H - N_BND, axis=0)
                    + se_c * dx,
                    jax.lax.slice_in_dim(zz, H - N_BND, H, axis=0),
                ],
                axis=0,
            )
            zy = jnp.concatenate(
                [
                    jax.lax.slice_in_dim(zx, 0, N_BND, axis=1),
                    jax.lax.slice_in_dim(zx, N_BND, W - N_BND, axis=1)
                    + se_c * dy,
                    jax.lax.slice_in_dim(zx, W - N_BND, W, axis=1),
                ],
                axis=1,
            )
            return zy + jnp.full(
                zy.shape, shift, jnp.float32
            ).astype(zz.dtype)
    elif mix == "dualdim_lean":
        # the EXACT op-diet dual-dim body (_dual_step_kernel lean=True):
        # difference-form taps with the per-axis scale FOLDED into the
        # two coefficients (5 vector ops/axis vs the 4-tap form's 8) and
        # ONE masked fused residual reduction (1 where + 1 sum vs
        # 2 where-free sums) — ~14 nominal ops/elt/rep. Same fold-back
        # recurrence as the dualdim mix; the residual mask excludes the
        # last derivative row (mixed true/false — fold-proof, the
        # round-4 constant-fold lesson) and numpy replicates it
        se_c = jnp.asarray(se, z.dtype)
        H, W = z.shape
        fc1 = float(np.float32(np.float32(0.0078125) * np.float32(_C1)))
        fc2 = float(np.float32(np.float32(0.0078125) * np.float32(_C2)))
        c1x = jnp.asarray(fc1, z.dtype)
        c2x = jnp.asarray(fc2, z.dtype)
        c1y, c2y = c1x, c2x  # probe uses sx == sy

        def body(_, zz):
            # both derivatives on the both-dims interior, exactly like
            # the kernel block (core = column-interior for dx, mid =
            # row-interior for dy; both (H-2G, W-2G))
            core = jax.lax.slice_in_dim(zz, N_BND, W - N_BND, axis=1)
            mid = jax.lax.slice_in_dim(zz, N_BND, H - N_BND, axis=0)

            def rs(off):
                return jax.lax.slice_in_dim(
                    core, N_BND + off, N_BND + off + H - 2 * N_BND,
                    axis=0,
                )

            def cs(off):
                return jax.lax.slice_in_dim(
                    mid, N_BND + off, N_BND + off + W - 2 * N_BND,
                    axis=1,
                )

            dx = c1x * (rs(1) - rs(-1)) + c2x * (rs(2) - rs(-2))
            dy = c1y * (cs(1) - cs(-1)) + c2y * (cs(2) - cs(-2))
            dxf = dx.astype(jnp.float32)
            dyf = dy.astype(jnp.float32)
            # one fused masked reduction; mask depends on the row iota
            # so nothing constant-folds, mirroring the kernel's ragged
            # last-block row mask (scalar chain stays f32 — bf16 scalar
            # arith does not legalize)
            rows = jax.lax.broadcasted_iota(jnp.int32, dxf.shape, 0)
            r = jnp.sum(jnp.where(
                rows < H - 2 * N_BND - 1, dxf * dxf + dyf * dyf,
                jnp.zeros((), jnp.float32),
            )) / 1024.0
            shift = jnp.asarray(se, jnp.float32) * r
            interior = (
                jax.lax.slice_in_dim(mid, N_BND, W - N_BND, axis=1)
                + se_c * dx + se_c * dy
            )
            stitched_mid = jnp.concatenate(
                [
                    jax.lax.slice_in_dim(mid, 0, N_BND, axis=1),
                    interior,
                    jax.lax.slice_in_dim(mid, W - N_BND, W, axis=1),
                ],
                axis=1,
            )
            zx = jnp.concatenate(
                [
                    jax.lax.slice_in_dim(zz, 0, N_BND, axis=0),
                    stitched_mid,
                    jax.lax.slice_in_dim(zz, H - N_BND, H, axis=0),
                ],
                axis=0,
            )
            return zx + jnp.full(
                zx.shape, shift, jnp.float32
            ).astype(zz.dtype)
    else:
        # step5_*: the EXACT k-step kernel body (_step5 + band concat)
        # applied to the resident block — 7 nominal ops/elt/rep (2 sub
        # + 2 mul + 1 add derivative, + mul + add update) plus whatever
        # the shifts and the concat stitching really cost; that
        # difference vs the fma mix is the point of the probe.
        # step5fma_*: the same update in raw 4-tap se-folded form —
        # old + Σ tᵢ·z₊ᵢ with tᵢ = se·STENCIL5ᵢ folded at trace time
        # (se is static here), 4 independent mul+add pairs with no
        # serial sub dependency. Built to test whether the dual-dim
        # op-diet lesson (raw 4-tap accumulation beat the difference
        # form ~1.4x in-VMEM via FMA fusion) transfers to the headline
        # body — it does NOT (BASELINE round-5 VPU note: diff/fma
        # 0.80-0.98x, difference form faster everywhere). Same real
        # arithmetic, different FP association; both variants share the
        # stitching below so the A/B stays like-for-like.
        axis = 0 if mix.endswith("_d0") else 1
        N = z.shape[axis]
        if mix.startswith("step5fma"):
            t1 = jnp.asarray(float(se) * _C1, z.dtype)
            tm1 = jnp.asarray(-float(se) * _C1, z.dtype)
            t2 = jnp.asarray(float(se) * _C2, z.dtype)
            tm2 = jnp.asarray(-float(se) * _C2, z.dtype)

            def upd_fn(zz):
                def zs(off):
                    return jax.lax.slice_in_dim(
                        zz, N_BND + off, N - N_BND + off, axis=axis
                    )

                return (zs(0) + t1 * zs(1) + tm1 * zs(-1)
                        + t2 * zs(2) + tm2 * zs(-2))
        else:
            se_t = jnp.asarray(se, z.dtype)

            def upd_fn(zz):
                return _step5(zz, N_BND, N - 2 * N_BND, axis, se_t)

        def body(_, z):
            return jnp.concatenate(
                [
                    jax.lax.slice_in_dim(z, 0, N_BND, axis=axis),
                    upd_fn(z),
                    jax.lax.slice_in_dim(z, N - N_BND, N, axis=axis),
                ],
                axis=axis,
            )

    out_ref[:] = jax.lax.fori_loop(0, reps, body, z)


@functools.partial(
    jax.jit, static_argnames=("reps", "mix", "se", "interpret")
)
def vpu_probe_pallas(z, reps: int, mix: str = "fma", se: float = 1e-9,
                     interpret: bool | None = None):
    """In-VMEM vector-op rate probe (round 4, VERDICT r3 next #3): load
    one block into VMEM, apply ``reps`` repetitions of an op mix with NO
    intermediate HBM traffic, write back. Differencing two ``reps``
    values cancels the launch overhead and the two HBM passes, leaving
    the pure per-rep VPU cost — the compute-axis twin of the
    stream-count family's bandwidth fit (``tpu/microbench.py streams``).

    Mixes: ``fma`` (elementwise a·z + b, 2 nominal ops/elt),
    ``step5_d0``/``step5_d1`` (the k-step stencil kernel's actual
    per-step body on the resident block: 7 nominal ops/elt plus
    sublane/lane shifts and the band concat; ``step5fma_d0``/``_d1``
    are the same update in raw 4-tap se-folded form — the refuted
    round-5 alternative, kept so the diff-vs-fma A/B stays
    reproducible via ``tpu/microbench.py vpu`` with
    ``TPU_MPI_VPU_STEP5FMA=1``), and — round 5, VERDICT r4
    #6 — ``heat5`` (the heat Laplacian streamer's exact per-step body:
    4 concat shifts + two-axis Euler update + border mask, ~11 nominal
    ops/elt) and ``dualdim`` (the dual-dim step kernel's body: 4-tap
    derivatives on both axes + TWO row-masked f32 squared-residual
    reductions, ~22 nominal ops/elt; ``dualdim_lean`` is the op-diet
    body — difference-form taps with the scale folded into the
    coefficients plus ONE fused masked residual reduction, ~14 nominal
    ops/elt). Mask-op convention for both counts: each ``where`` select
    feeding a reduction counts as one op/elt — dualdim's 22 includes
    its two masks exactly as dualdim_lean's 14 includes its one.
    The ratio of a kernel mix's rate to the fma rate
    prices its shifts/reductions; each hand kernel's marginal element
    rate over its own mix's probe rate is the fraction of the VPU
    ceiling it reaches (``tpu/microbench.py vpu``/``roofline2``).

    ``z`` must be small enough to keep ~4 block-sized live buffers under
    the VMEM budget ((512, 512) f32 = 1 MB blocks in practice). The
    output aliases ``z`` so the probe chains. ``se`` is the step5 update
    scale: the 1e-9 default keeps kilorep chains numerically inert for
    timing; tests pass a visible value so the arithmetic is checkable
    (at 1e-9 the update underflows f32 against O(100) fields)."""
    total = int(np.prod(z.shape)) * jnp.dtype(z.dtype).itemsize
    if 4 * total > _VMEM_BUDGET_BYTES:
        raise ValueError(
            f"vpu_probe_pallas: block {z.shape} needs ~4x"
            f"{total} B live in VMEM, over the "
            f"{_VMEM_BUDGET_BYTES // 2**20} MB budget"
        )
    if mix not in ("fma", "step5_d0", "step5_d1", "step5fma_d0",
                   "step5fma_d1", "heat5", "dualdim", "dualdim_lean"):
        raise ValueError(f"unknown mix {mix!r}")
    return pl.pallas_call(
        functools.partial(_vpu_probe_kernel, reps=reps, mix=mix, se=se),
        out_shape=jax.ShapeDtypeStruct(z.shape, z.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        input_output_aliases={0: 0},
        interpret=_auto_interpret(interpret),
    )(z)


# ---------------------------------------------------------------------------
# 2-D array, 1-D 5-point stencil with explicit halo DMA
# ---------------------------------------------------------------------------


def _stencil_strip_kernel(z_ref, scale_ref, out_ref, *, axis, m):
    # full ghosted extent along `axis` is resident in VMEM; the 5 shifted
    # reads become VPU shifts, accumulated in registers (≅ the SYCL kernel's
    # 5 global loads per output element, but staged once)
    z = z_ref[:]
    acc = None
    # .tolist() → weak python floats: no x64 promotion of f32 blocks
    for k, c in enumerate(STENCIL5.tolist()):
        if c == 0.0:
            continue
        term = c * jax.lax.slice_in_dim(z, k, k + m, axis=axis)
        acc = term if acc is None else acc + term
    out_ref[:] = acc * scale_ref[0]


# Mosaic's scoped-vmem limit is 16 MiB on v5e — measured, not assumed:
# tpu/vmemprobe.py bisects the minimal compiling limit per kernel config
# and the tallest passing/failing configs bracket the default at 16 MiB
# (round 3, VERDICT r2 weak #6). TWO budgets against it:
#
# * ``_VMEM_BUDGET_CAL`` (15 MiB, ~1 MiB headroom) — ONLY for live-set
#   models the probe validated to a few percent: the k-step iterate
#   strips and the ``_stream_live_bytes`` row-streaming family. Those
#   models are calibrated: block I/O is double-buffered at the array
#   dtype, but Mosaic's per-op temps are f32-sized for narrow dtypes
#   (they do NOT shrink below 32-bit — the round-2 bf16 models that
#   scaled everything by itemsize under-counted by ~1.6×, which is
#   exactly how the bf16 S=2 "compile flake" happened: a 256-wide strip
#   passed the model at 9.9 MB, 20.5 MB real). Wider-than-f32 dtypes are
#   UNMEASURED, so temps take max(f32-calibrated, itemsize-scaled).
# * ``_VMEM_BUDGET_BYTES`` (14 MiB) — every other consumer (flash tile
#   fitters, ring collectives, the 2-buffer derivative strips), whose
#   models are incident-calibrated, keeps the round-2 margin.
_VMEM_BUDGET_BYTES = 14 * 1024 * 1024
_VMEM_BUDGET_CAL = 15 * 1024 * 1024


def _strip_rows_bytes(extent: int, itemsize: int) -> int:
    """Calibrated live bytes per unit strip of a k-step iterate kernel
    (vmemprobe bisections, round 3): double-buffered aliased I/O at the
    array dtype (4·itemsize) plus ~3 per-step temps that are f32-sized
    for narrow dtypes and itemsize-sized above f32 (unmeasured wider
    dtypes take the conservative max). Measured 28.05 B/elt f32 (both
    dims), 19.4/17.9 bf16 vs the model's 28/20."""
    return extent * (4 * itemsize + max(12, 3 * itemsize))


def _d1_strip_rows_bytes(ny: int, dtype) -> int:
    """Dim-1 k-step strip live bytes per row: BFLOAT16 (specifically —
    the coefficient was bisected on bf16 kernels; float16 may legalize
    via f32 widening and keeps the conservative shared model) has its
    own measured coefficient (17.91 B/elt probed at strip 88 ·1.05
    margin — the shared `_strip_rows_bytes` bf16 value must stay ≥ the
    d0 kernel's 19.53 and left d1 at 1.11 conservative); other dtypes
    share the common model."""
    if jnp.dtype(dtype) == jnp.bfloat16:
        return int(ny * 18.8)
    return _strip_rows_bytes(ny, jnp.dtype(dtype).itemsize)


def _kstep_d1_strip(nx: int, ny: int, dtype, tile: int) -> int:
    """Dim-1 strip for the k-step iterate: the largest 8-multiple ≤
    ``tile`` that fits the calibrated budget, computed DIRECTLY (the
    halving fit could not land between power-of-2 steps; the direct
    fit makes the cap honest. The calibrated bf16 budget admits 96-row
    strips, but the round-4 interleaved re-sweep measured 64/88/96 FLAT
    within contention noise (±3%, 64 marginally ahead), so the
    production tile cap stays 64 and wider strips remain an explicit
    ``tile=`` opt-in; f32's budget-max is 68 → 64 either way)."""
    rows_bytes = _d1_strip_rows_bytes(ny, dtype)
    budget_max = (_VMEM_BUDGET_CAL // rows_bytes) // 8 * 8
    tile = max(8, tile // 8 * 8)  # keep the documented 8-multiple contract
    strip = min(min(tile, nx), max(8, budget_max))
    if strip * rows_bytes > _VMEM_BUDGET_CAL:
        raise ValueError(
            f"stencil2d iterate dim-1: even an 8-row strip of width {ny} "
            f"exceeds the VMEM budget; use the XLA stencil"
        )
    return strip


def _fit_strip(tile: int, extent: int, rows_bytes: int, min_strip: int,
               budget: int = _VMEM_BUDGET_BYTES) -> int:
    """Largest strip ≤ tile fitting the VMEM ``budget``. ``rows_bytes``
    is the caller's live-set bytes per unit strip — the one-step
    derivative kernel's 2·(ghosted+interior)·itemsize (incident-
    calibrated, default budget), or :func:`_strip_rows_bytes` for the
    k-step iterate (probe-calibrated; pass ``budget=_VMEM_BUDGET_CAL``).
    Shrinking keeps strips at multiples of ``min_strip`` — lane-dim
    strips must stay 128-multiples (the Mosaic block rule) and sublane
    strips 8-multiples. Ragged final blocks are fine — pallas masks
    out-of-bounds loads/stores."""
    strip = min(tile, extent)
    while strip > min_strip and strip * rows_bytes > budget:
        strip = max(min_strip, (strip // 2) // min_strip * min_strip)
    if strip * rows_bytes > budget:
        raise ValueError(
            f"stencil2d_pallas: even a {strip}-wide strip of extent "
            f"{extent} exceeds the VMEM budget; use the XLA stencil"
        )
    return strip


@functools.partial(jax.jit, static_argnames=("dim", "tile", "interpret"))
def stencil2d_pallas(
    z,
    scale,
    dim: int = 0,
    tile: int = 256,
    interpret: bool | None = None,
):
    """5-point first derivative along ``dim`` of a 2-D array ghosted along
    ``dim`` (out = in − 2·N_BND there) as a hand-tiled Pallas kernel
    (≅ the SYCL ``stencil2d_1d_5``, ``mpi_stencil2d_sycl.cc:53-75``).

    Tiling: the grid walks the NON-derivative dim in ``tile``-wide strips;
    each strip holds the full ghosted derivative extent in VMEM (Mosaic
    requires HBM slices 8-sublane-aligned, which ghosted interiors never
    are, so the halo travels with the strip). Strips auto-shrink to the
    VMEM budget (see ``_fit_strip``); ragged final strips are masked by the pallas pipeline.
    Extents too large for even a minimum strip stream blocks instead —
    rows for ``dim=0`` (``_stencil_stream0``), columns for ``dim=1``
    (``_stencil_stream1``; round 3) — so NO shape falls back to XLA: both
    decomposition dims have unbounded extent.
    """
    nx, ny = z.shape
    if dim == 0:
        mx, mn = nx - 2 * N_BND, ny  # out shape
        # lane-dim strips must stay 128-multiples (Mosaic block rule) —
        # rounded up here AND preserved by _fit_strip's shrinking; arrays
        # too tall for even a 128-lane strip stream row blocks instead
        # (round 2 removed the fall-back-to-XLA height limit)
        tile = max(128, -(-tile // 128) * 128)
        try:
            strip = _fit_strip(
                tile, mn, 2 * (nx + mx) * z.dtype.itemsize, min_strip=128
            )
        except ValueError:
            return _stencil_stream0(
                z, jnp.asarray(scale, z.dtype).reshape(1), interpret
            )
        grid = (pl.cdiv(mn, strip),)
        in_spec = pl.BlockSpec(
            (nx, strip), lambda j: (0, j), memory_space=pltpu.VMEM
        )
        out_spec = pl.BlockSpec(
            (mx, strip), lambda j: (0, j), memory_space=pltpu.VMEM
        )
        kernel = functools.partial(_stencil_strip_kernel, axis=0, m=mx)
        out_shape = (mx, mn)
    else:
        mx, mn = nx, ny - 2 * N_BND
        try:
            strip = _fit_strip(
                tile, mx, 2 * (ny + mn) * z.dtype.itemsize, min_strip=8
            )
        except ValueError:
            return _stencil_stream1(
                z, jnp.asarray(scale, z.dtype).reshape(1), interpret
            )
        grid = (pl.cdiv(mx, strip),)
        in_spec = pl.BlockSpec(
            (strip, ny), lambda i: (i, 0), memory_space=pltpu.VMEM
        )
        out_spec = pl.BlockSpec(
            (strip, mn), lambda i: (i, 0), memory_space=pltpu.VMEM
        )
        kernel = functools.partial(_stencil_strip_kernel, axis=1, m=mn)
        out_shape = (mx, mn)

    scale_arr = jnp.asarray(scale, z.dtype).reshape(1)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, z.dtype),
        grid=grid,
        in_specs=[in_spec, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=out_spec,
        interpret=_auto_interpret(interpret),
    )(z, scale_arr)


def _stencil_stream0_kernel(z_ref, bot_ref, scale_ref, out_ref, *, B):
    """Row-streaming dim-0 derivative block: the (B, P) output needs input
    rows [i·B, i·B+B+2·N_BND) — its own block plus a 2·N_BND-row bottom
    edge riding as a gathered side operand (same trick as
    ``_iterate_stream0_kernel``, one-sided because the derivative output
    is offset by the lo ghost already)."""
    window = jnp.concatenate([z_ref[:], bot_ref[0]], axis=0)
    acc = None
    for k, c in enumerate(STENCIL5.tolist()):
        if c == 0.0:
            continue
        term = c * jax.lax.slice_in_dim(window, k, k + B, axis=0)
        acc = term if acc is None else acc + term
    out_ref[:] = acc * scale_ref[0]


def _stencil_stream0(z, scale_arr, interpret):
    """Streaming dim-0 path of :func:`stencil2d_pallas` for domains whose
    full ghosted height exceeds VMEM (the round-2 fallback-to-XLA case)."""
    nx, ny = z.shape
    mx = nx - 2 * N_BND
    E = 2 * N_BND
    itemsize = jnp.dtype(z.dtype).itemsize
    sub = max(8, 8 * 4 // itemsize)
    # window rows = B + E = B + 2·K at K=N_BND — the iterate fit applies
    B, P = _fit_stream0_blocks(
        ny, N_BND, itemsize, sub,
        bf16_temps=_BF16_TEMPS_DERIV_STREAM,
    )
    nb = pl.cdiv(mx, B)
    _, bot = _row_block_edges(z, B, E, nb)
    return pl.pallas_call(
        functools.partial(_stencil_stream0_kernel, B=B),
        out_shape=jax.ShapeDtypeStruct((mx, ny), z.dtype),
        grid=(nb, pl.cdiv(ny, P)),
        in_specs=[
            pl.BlockSpec((B, P), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, E, P), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((B, P), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        interpret=_auto_interpret(interpret),
    )(z, bot, scale_arr)


def _stencil_stream1_kernel(z_ref, right_ref, scale_ref, out_ref, *, B):
    """Column-streaming dim-1 derivative block: the (P, B) output needs
    input columns [j·B, j·B+B+2·N_BND) — its own block plus a
    2·N_BND-wide RIGHT edge riding as a gathered side operand (the
    column mirror of ``_stencil_stream0_kernel``; one-sided because the
    derivative output is offset by the lo ghost already)."""
    window = jnp.concatenate([z_ref[:], right_ref[0]], axis=1)
    acc = None
    for k, c in enumerate(STENCIL5.tolist()):
        if c == 0.0:
            continue
        term = c * jax.lax.slice_in_dim(window, k, k + B, axis=1)
        acc = term if acc is None else acc + term
    out_ref[:] = acc * scale_ref[0]


def _stencil_stream1(z, scale_arr, interpret):
    """Streaming dim-1 path of :func:`stencil2d_pallas` for domains whose
    full ghosted WIDTH exceeds VMEM (round 3 — the last
    fall-back-to-XLA shape limit, VERDICT r2 weak #5): grid over row
    panels × column blocks, with each block's 2·N_BND-column right edge
    as a gathered side operand shaped (nb, nx, E) — block-indexed dim
    leading per the Mosaic block rule (last two block dims must be
    sublane/lane aligned or whole)."""
    nx, ny = z.shape
    mn = ny - 2 * N_BND
    E = 2 * N_BND
    itemsize = jnp.dtype(z.dtype).itemsize
    sub = max(8, 8 * 4 // itemsize)
    # the row-streaming fit transposes cleanly: its row block (8-mult,
    # ≤256) is our row PANEL, its column panel (128-mult, ≤1024) is our
    # column BLOCK; the live-set model differs only in which side carries
    # the ±2-element halo
    P, B = _fit_stream0_blocks(
        ny, N_BND, itemsize, sub,
        label="stencil2d streaming dim-1 (transposed window: rows×cols)",
        bf16_temps=_BF16_TEMPS_DERIV_STREAM,
    )
    nb = pl.cdiv(mn, B)
    # right edge of out-column block j = input columns [jB+B, jB+B+E);
    # strided view of z shifted one block left, padded to nb blocks
    zs = z[:, B:]
    total = nb * B
    if zs.shape[1] < total:
        zs = jnp.pad(zs, ((0, 0), (0, total - zs.shape[1])))
    right = jnp.transpose(
        zs[:, :total].reshape(nx, nb, B)[:, :, :E], (1, 0, 2)
    )
    return pl.pallas_call(
        functools.partial(_stencil_stream1_kernel, B=B),
        out_shape=jax.ShapeDtypeStruct((nx, mn), z.dtype),
        grid=(pl.cdiv(nx, P), nb),
        in_specs=[
            pl.BlockSpec((P, B), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, P, E), lambda i, j: (j, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((P, B), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        interpret=_auto_interpret(interpret),
    )(z, right, scale_arr)


# STENCIL5 is antisymmetric (central first derivative): emit the 2-difference
# form c1·(z₊₁−z₋₁) + c2·(z₊₂−z₋₂) — 5 VPU ops/elt vs 7 for the raw 4-tap
# accumulation. The kernels assert this so a changed table can't silently
# produce wrong differences.
_C1, _C2 = float(STENCIL5[3]), float(STENCIL5[4])
assert np.allclose(STENCIL5, [-_C2, -_C1, 0.0, _C1, _C2])


def _step5(z, lo, span, axis, se):
    """One update for positions [lo, lo+span): old + se·(c₁(z₊₁−z₋₁) +
    c₂(z₊₂−z₋₂)) — difference form, 5 VPU ops/elt vs 7 for the raw 4-tap
    accumulation. (A serial two-FMA variant pre-folding se into the
    coefficients measured no better on the shared chip; the A/B was within
    its ±5% contention window, so the simpler form that keeps XLA's
    se·acc rounding is kept.)"""

    def zs(off):
        return jax.lax.slice_in_dim(z, lo + off, lo + off + span, axis=axis)

    acc = _C1 * (zs(1) - zs(-1)) + _C2 * (zs(2) - zs(-2))
    return zs(0) + se * acc


def _masked_step(window, lo, hi, axis, se, abs0, dlo, dhi):
    """One masked in-window step, shared by the whole-shard kernel's
    dynamic-flag path and the row-streaming kernel: update [lo, hi),
    keeping rows whose ABSOLUTE index (window position + ``abs0``) falls
    outside [dlo, dhi) at their previous value, and stitch the window."""
    upd = _step5(window, lo, hi - lo, axis, se)
    old = jax.lax.slice_in_dim(window, lo, hi, axis=axis)
    io = jax.lax.broadcasted_iota(jnp.int32, upd.shape, axis) + lo + abs0
    upd = jnp.where((io >= dlo) & (io < dhi), upd, old)
    W = window.shape[axis]
    return jnp.concatenate(
        [
            jax.lax.slice_in_dim(window, 0, lo, axis=axis),
            upd,
            jax.lax.slice_in_dim(window, hi, W, axis=axis),
        ],
        axis=axis,
    )


def _iterate_kernel(
    z_ref, scale_eps_ref, *rest, axis, steps, phys_static
):
    # axis 1: stencil taps ride the lane dim (register-cheap shifts);
    # axis 0: sublane-dim shifts — costlier in the VPU, which is exactly
    # what the dim-0 benchmark rows measure.
    #
    # steps > 1 is communication-avoiding temporal blocking: the strip is
    # advanced `steps` timesteps while resident in VMEM, one HBM read+write
    # serving them all. Ghost width must be steps·N_BND (deep halo); the
    # valid update span shrinks by N_BND per side per step, so after k steps
    # the true interior holds exactly what k (exchange+step) iterations
    # produce. Physical (non-periodic edge-shard) sides keep their boundary
    # band fixed every step — the per-step scheme's Dirichlet band — instead
    # of shrinking. When the flags are known at trace time (``phys_static``:
    # always for world=1 or periodic rings) the spans are static slices;
    # otherwise an SMEM flag pair drives an iota mask (edge shards of a
    # non-periodic multi-chip ring).
    if phys_static is None:
        phys_ref, out_ref = rest
    else:
        (out_ref,) = rest
    z = z_ref[:]
    N = z.shape[axis]
    se = scale_eps_ref[0]
    K = steps * N_BND
    for s in range(1, steps + 1):
        if phys_static is not None:
            lo_b = K if phys_static[0] else s * N_BND
            hi_b = N - (K if phys_static[1] else s * N_BND)
            upd = _step5(z, lo_b, hi_b - lo_b, axis, se)
            z = jnp.concatenate(
                [
                    jax.lax.slice_in_dim(z, 0, lo_b, axis=axis),
                    upd,
                    jax.lax.slice_in_dim(z, hi_b, N, axis=axis),
                ],
                axis=axis,
            )
        else:
            dlo = jnp.where(phys_ref[0] != 0, K, s * N_BND)
            dhi = jnp.where(phys_ref[1] != 0, N - K, N - s * N_BND)
            z = _masked_step(z, N_BND, N - N_BND, axis, se, 0, dlo, dhi)
    out_ref[:] = z


def _kstep_advance(window, *, masked, steps, K, R, abs0, se,
                   phys_lo, phys_hi, phys_static):
    """The k-step window advance shared by the row-streaming kernel and
    the fused RDMA kernel — ONE implementation is what makes their
    per-cell arithmetic (and therefore the fused-vs-chained bitwise
    contract, ISSUE 15) structural rather than copy-paste-maintained.
    ``masked`` blocks clamp the per-step update band to the absolute
    span [dlo, dhi) (physical sides keep their fixed K band,
    exchange-fed sides shrink by N_BND per step); mask-free blocks run
    the raw maximal-span update."""
    W = window.shape[0]
    N = N_BND
    for s in range(1, steps + 1):
        lo = s * N
        hi = W - s * N
        if masked:
            if phys_static is not None:
                dlo = K if phys_lo else lo
                dhi = R - (K if phys_hi else lo)
            else:
                dlo = jnp.where(phys_lo, K, lo)
                dhi = jnp.where(phys_hi, R - K, R - lo)
            window = _masked_step(window, lo, hi, 0, se, abs0, dlo, dhi)
        else:
            upd = _step5(window, lo, hi - lo, 0, se)
            window = jnp.concatenate(
                [
                    jax.lax.slice_in_dim(window, 0, lo, axis=0),
                    upd,
                    jax.lax.slice_in_dim(window, hi, W, axis=0),
                ],
                axis=0,
            )
    return window


def _iterate_stream0_kernel(z_ref, top_ref, bot_ref, scale_eps_ref, *rest,
                            steps, B, K, R, i_lo_mask, i_hi_mask,
                            phys_static):
    """Row-streaming dim-0 k-step update for domains too tall to hold the
    full ghosted height in VMEM. Each grid cell (i, j) advances one
    (B, P) row×column block k timesteps on a (B+2K, P) window assembled
    from the block plus K-row neighbor edges (separate gathered operands —
    blocked specs mean Mosaic pipelines all the fetches; no manual DMA, so
    no tile-alignment constraints on K or B beyond the usual block rules).

    The per-step maximal span [s·N, W−s·N) is EXACTLY the influence cone
    of the output rows (K = steps·N), so interior blocks need no masking
    at all; only blocks whose window reaches the global lo/hi bands take
    the masked branch (``lax.cond`` on the row-block id), keeping the VPU
    cost of the hot path at the short-shard kernel's 5 ops/elt/step."""
    if phys_static is None:
        phys_ref, out_ref = rest
        phys_lo = phys_ref[0] != 0
        phys_hi = phys_ref[1] != 0
    else:
        (out_ref,) = rest
        phys_lo, phys_hi = bool(phys_static[0]), bool(phys_static[1])
    se = scale_eps_ref[0]
    i = pl.program_id(0)
    window = jnp.concatenate([top_ref[0], z_ref[:], bot_ref[0]], axis=0)
    abs0 = i * B - K  # absolute (ghosted) row index of window position 0

    advance = functools.partial(
        _kstep_advance, steps=steps, K=K, R=R, abs0=abs0, se=se,
        phys_lo=phys_lo, phys_hi=phys_hi, phys_static=phys_static,
    )
    needs_mask = (i < i_lo_mask) | (i >= i_hi_mask)
    window = jax.lax.cond(
        needs_mask,
        functools.partial(advance, masked=True),
        functools.partial(advance, masked=False),
        window,
    )
    out_ref[:] = jax.lax.slice_in_dim(window, K, K + B, axis=0)


# Measured bf16 per-window-element temp coefficients (vmemprobe round-4
# bisections + ~5% safety): the k-step iterate streamer's Mosaic temps
# cost 17.51 B/elt at bf16, the heat Laplacian streamer's 14.57 — both
# well under the f32-sized 22 the round-3 model charged (model/actual
# 1.18/1.34, a third of the budget wasted exactly where window width
# sets streaming throughput). Kernels WITHOUT a vmemprobe config keep
# the conservative default.
_BF16_TEMPS_DEFAULT = 22.0
_BF16_TEMPS_ITER_STREAM = 18.4   # 17.51 measured · 1.05
_BF16_TEMPS_HEAT = 15.3          # 14.57 measured · 1.05
# round-5 calibrations (VERDICT r4 #4 — the last two consumers of the
# shared model, previously budgeting blind at the conservative default):
# solved from the round-5 vmemprobe bisected actuals via the shared
# live-set form (temps = (actual − 4·itemsize·B·W) / (window·W)). The
# one-step derivative streamer's temps are far below every k-step
# kernel's — one output, no multi-step window carry — and the dual-dim
# coefficient admits 256-row blocks at ≤~2.8k widths (re-swept, see
# BASELINE round-5 calibration note)
_BF16_TEMPS_DERIV_STREAM = 5.7    # 5.36 measured · 1.05
_BF16_TEMPS_DUAL_DIM = 10.4      # 9.88 measured · 1.05


def _stream_live_bytes(B: int, halo: int, width: int, itemsize: int,
                       bf16_temps: float = _BF16_TEMPS_DEFAULT,
                       extra_temps: float = 0.0) -> int:
    """The row-streaming kernels' shared VMEM live-set model, calibrated
    against Mosaic's actual high-water marks (tpu/vmemprobe.py
    bisection): double-buffered I/O blocks at the array dtype plus
    per-window-element temps. Temps are F32-SIZED for narrow dtypes by
    default (they do not shrink with the dtype — the round-2
    ``8 × window × itemsize`` form under-counted bf16 by ~1.6×) and
    itemsize-scaled above f32 (wider dtypes are unmeasured; take the
    conservative max); kernels with a round-4 vmemprobe calibration pass
    their measured bf16 coefficient via ``bf16_temps`` (f32 stays at 22
    vs 20.2–20.8 measured — already within 5–8%). Measured model/actual
    after calibration: iterate-stream bf16 1.05, heat bf16 1.05 (was
    1.18/1.34)."""
    if itemsize == 2:
        temps = bf16_temps
    else:
        temps = max(22, 11 * itemsize // 2)
    # extra_temps: additional per-window-element live bytes a kernel
    # VARIANT keeps beyond the calibrated mix (heat border_coeff's two
    # coefficient arrays = 2·itemsize)
    return int(4 * itemsize * B * width
               + (temps + extra_temps) * (B + 2 * halo) * width)


def _fit_block_rows(width: int, halo: int, itemsize: int, sub: int,
                    bf16_temps: float = _BF16_TEMPS_DEFAULT,
                    extra_temps: float = 0.0) -> int:
    """Largest sublane-multiple row block ≤ 256 whose live set fits VMEM
    (floor: one sublane tile). B starts at 256: the 8192² k=4 sweep
    measured 128–256-row blocks fastest (2090–2180 iter/s) and 512
    slowest — small blocks keep the pipeline deep without starving the
    VPU."""
    B = 256
    while B > sub and _stream_live_bytes(B, halo, width, itemsize,
                                         bf16_temps, extra_temps) > \
            _VMEM_BUDGET_CAL:
        B = max(sub, (B // 2) // sub * sub)
    return B


def _validate_tile_rows(tile_rows: int, sub: int,
                        name: str = "tile_rows") -> None:
    if tile_rows % sub:
        raise ValueError(
            f"{name}={tile_rows} must be a multiple of the "
            f"{sub}-row sublane tile"
        )


def _stream_fit(z, halo: int, kernel_name: str,
                tile_rows: "int | None",
                bf16_temps: float = _BF16_TEMPS_DEFAULT,
                extra_temps: float = 0.0) -> int:
    """Shared full-width streaming preamble: fitted row block ``B`` (with
    the VMEM-budget raise callers' fallbacks match on) and the optional
    test-hook clamp."""
    width = z.shape[1]
    itemsize = jnp.dtype(z.dtype).itemsize
    sub = max(8, 8 * 4 // itemsize)
    B = _fit_block_rows(width, halo, itemsize, sub, bf16_temps,
                        extra_temps)
    if _stream_live_bytes(B, halo, width, itemsize,
                          bf16_temps, extra_temps) > _VMEM_BUDGET_CAL:
        raise ValueError(
            f"{kernel_name}: width {width} exceeds the VMEM budget even "
            f"at {B}-row blocks; use the XLA tier"
        )
    if tile_rows is not None:
        _validate_tile_rows(tile_rows, sub)
        B = min(B, tile_rows)
    return B


def _fit_stream0_blocks(ny: int, K: int, itemsize: int, sub: int,
                        label: str = "stencil2d streaming dim-0",
                        bf16_temps: float = _BF16_TEMPS_DEFAULT):
    """(B, P) for the streaming stencil kernels (shared live-set model
    above; columns panel down to 128 lanes before giving up). The dim-1
    column streamer reuses the fit with the roles transposed and passes
    its own ``label`` so failures name the right decomposition."""
    P = min(-(-ny // 128) * 128, 1024)
    B = _fit_block_rows(P, K, itemsize, sub, bf16_temps)
    while P > 128 and _stream_live_bytes(B, K, P, itemsize,
                                         bf16_temps) > \
            _VMEM_BUDGET_CAL:
        P //= 2
    if _stream_live_bytes(B, K, P, itemsize,
                          bf16_temps) > _VMEM_BUDGET_CAL:
        raise ValueError(
            f"{label}: even a ({B}+2·{K})×{P} window "
            f"exceeds the VMEM budget"
        )
    return B, P


def _iterate_stream0(z, se, steps, phys, phys_static, interpret,
                     tile_rows):
    """Streaming dim-0 path of :func:`stencil2d_iterate_pallas` (tall
    domains): grid over row blocks × column panels; K-row top/bottom
    neighbor edges ride as gathered side operands."""
    nx, ny = z.shape
    K = steps * N_BND
    sub = max(8, 8 * 4 // jnp.dtype(z.dtype).itemsize)
    B, P = _fit_stream0_blocks(
        ny, K, jnp.dtype(z.dtype).itemsize, sub,
        bf16_temps=(_BF16_TEMPS_ITER_STREAM
                    if jnp.dtype(z.dtype) == jnp.bfloat16
                    else _BF16_TEMPS_DEFAULT),
    )
    if tile_rows is not None:
        _validate_tile_rows(tile_rows, sub, name="stream_tile_rows")
        B = min(B, tile_rows)
    nb = pl.cdiv(nx, B)
    # per-block static masking decision (see kernel docstring): block i is
    # mask-free iff its window stays inside the worst-case update bands
    # [2K−N, R−2K+N) at every step
    i_lo_mask = -(-(2 * K - N_BND) // B)
    i_hi_mask = (nx - B - 2 * K + N_BND) // B + 1
    top, bot = _row_block_edges(z, B, K, nb)
    in_specs = [
        pl.BlockSpec((B, P), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, K, P), lambda i, j: (i, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, K, P), lambda i, j: (i, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [z, top, bot, se]
    if phys_static is None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.asarray(phys, jnp.int32).reshape(2))
    return pl.pallas_call(
        functools.partial(
            _iterate_stream0_kernel,
            steps=steps,
            B=B,
            K=K,
            R=nx,
            i_lo_mask=i_lo_mask,
            i_hi_mask=i_hi_mask,
            phys_static=phys_static,
        ),
        out_shape=jax.ShapeDtypeStruct((nx, ny), z.dtype),
        grid=(nb, pl.cdiv(ny, P)),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (B, P), lambda i, j: (i, j), memory_space=pltpu.VMEM
        ),
        input_output_aliases={0: 0},
        interpret=_auto_interpret(interpret),
    )(*operands)


@functools.partial(
    jax.jit,
    static_argnames=(
        "dim", "tile", "interpret", "steps", "phys_static", "stream",
        "stream_tile_rows",
    ),
    donate_argnums=0,
)
def stencil2d_iterate_pallas(
    z,
    scale_eps,
    dim: int = 1,
    tile: int = 64,
    interpret: bool | None = None,
    steps: int = 1,
    phys=None,
    phys_static: "tuple[int, int] | None" = None,
    stream: bool | None = None,
    stream_tile_rows: int | None = None,
):
    """``steps`` in-place Jacobi-style steps: ``interior += scale_eps ·
    stencil`` along ``dim``, ghosts preserved — shape-preserving so calls
    chain, with the input buffer aliased to the output (true in-place; ≅ the
    reference updating ``d_dz`` from ``d_z`` each hot-loop iteration with
    persistent buffers, ``mpi_stencil2d_sycl.cc:218-239``).

    ``stream`` (dim-0 only): ``None`` auto-selects — the full-ghosted-height
    strip path when it fits VMEM, else the row-streaming kernel
    (``_iterate_stream0_kernel``), which removes the round-2 height limit
    (~6k f32 rows); ``True``/``False`` force a path (tests A/B them).
    ``stream_tile_rows`` caps the streaming row block below the auto-fit
    (its own knob — ``tile`` is the dim-1/strip lane width and does not
    leak into the streaming geometry).

    Two HBM passes per call (read z, write z) versus XLA's 6 (one per
    stencil tap + writes). ``dim=1`` puts the stencil taps on the lane dim,
    where VMEM shifts are register-cheap — the bench.py fast path; ``dim=0``
    shifts along sublanes (the reference's non-contiguous decomposition) at
    the same 2-pass traffic, so the dim-0 vs dim-1 A/B isolates the shift
    cost.

    ``steps=k`` amortizes the two passes over k timesteps (temporal
    blocking): the ghost width along ``dim`` must then be ``k·N_BND`` (deep
    halo, exchanged once per k steps — same exchanged volume as k shallow
    exchanges, 1/k the messages and 2/k the HBM passes per timestep). The
    interior after the call is bit-identical in structure to k single-step
    calls with per-step exchange. Physical (fixed-boundary, non-periodic
    edge shard) lo/hi sides are flagged either statically
    (``phys_static=(lo, hi)`` — compiles to static update spans, the fast
    path) or dynamically (``phys``, a (2,) int array — an SMEM-driven iota
    mask, for shard_map bodies where the shard index is traced). With
    neither, both sides are exchange-fed. Irrelevant at steps=1.
    """
    nx, ny = z.shape
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if stream and dim != 0:
        raise ValueError("stream=True applies to dim=0 only (dim-1 strips "
                         "already stream along the non-stencil axis)")
    if z.shape[dim] <= 2 * steps * N_BND:
        raise ValueError(
            f"extent {z.shape[dim]} along dim {dim} too small for "
            f"{steps}-step ghost width {2 * steps * N_BND}"
        )
    se = jnp.asarray(scale_eps, z.dtype).reshape(1)
    if steps == 1 or (phys is None and phys_static is None):
        phys_static = (0, 0)  # spans coincide at s=1, flags irrelevant
        phys = None
    # probe-calibrated live model + budget (_strip_rows_bytes /
    # _VMEM_BUDGET_CAL): same per-ghosted-element cost on both dims —
    # measured 28.05 B/elt f32 (d0 and d1), 19.4/17.9 bf16 (d0/d1) vs
    # the model's 28/20
    itemsize = z.dtype.itemsize
    if dim == 1:
        strip = _kstep_d1_strip(nx, ny, z.dtype, tile)
        grid = (pl.cdiv(nx, strip),)
        block = (strip, ny)
        index_map = lambda i: (i, 0)  # noqa: E731
    else:
        # lane strips must be 128-multiples (Mosaic block rule) and the
        # FULL ghosted height rides in VMEM, so nx+2·K is bounded by
        # ~14MB/(4·128·itemsize) — ≈6k rows f32; taller dim-0 domains
        # stream row blocks instead (round-2's height limit, removed)
        d0_rows_bytes = _strip_rows_bytes(nx, itemsize)
        if stream is None:
            try:
                _fit_strip(128, ny, d0_rows_bytes, min_strip=128,
                           budget=_VMEM_BUDGET_CAL)
            except ValueError:
                stream = True
        if stream:
            return _iterate_stream0(
                z, se, steps, phys, phys_static, interpret,
                stream_tile_rows,
            )
        tile0 = max(128, -(-tile // 128) * 128)
        strip = _fit_strip(tile0, ny, d0_rows_bytes, min_strip=128,
                           budget=_VMEM_BUDGET_CAL)
        grid = (pl.cdiv(ny, strip),)
        block = (nx, strip)
        index_map = lambda j: (0, j)  # noqa: E731
    in_specs = [
        pl.BlockSpec(block, index_map, memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [z, se]
    if phys_static is None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.asarray(phys, jnp.int32).reshape(2))
    return pl.pallas_call(
        functools.partial(
            _iterate_kernel, axis=dim, steps=steps, phys_static=phys_static
        ),
        out_shape=jax.ShapeDtypeStruct((nx, ny), z.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(block, index_map, memory_space=pltpu.VMEM),
        input_output_aliases={0: 0},
        interpret=_auto_interpret(interpret),
    )(*operands)


def _row_block_edges(z, B: int, G: int, nb: int):
    """(nb, G, ny) top and bottom G-row neighbor edges for each B-row
    block of ``z``, built with shift+pad+reshape slicing — any G, any B.
    (Chosen over the obvious clamped-index row gather by a same-window
    in-kernel A/B on v5e: equal for the iterate, 19% faster for heat;
    see BASELINE.md for the measurement history.) Rows that fall outside
    ``z`` (block 0's top, and the last block's bottom when the blocking
    covers all of ``z``) are ZERO-FILLED rather than left to wrap around
    the array: every caller's influence-cone masking makes them
    unreachable, but a masking bug then surfaces as a visible numeric
    error instead of plausible wrapped values (round-2 advisor
    finding)."""
    nx, ny = z.shape
    total = nb * B
    if G <= B:
        # fast path: ONE shared end-pad of z, then both edges are small
        # slices of the (nb2, B, ny) view rolled one block — top_i =
        # tails[i−1] = z[iB−G : iB], bot_i = heads[i+1] = z[iB+B : iB+B+G].
        # (An earlier formulation built each edge from its own full-array
        # concat+pad+reshape chain; XLA materialized those as whole-array
        # passes — the streaming iterate measured 1800 vs 2900 iter/s
        # same-window at 4096×8192 before/after this form, which touches
        # z once and otherwise only the small slices.) nb2 covers ALL of
        # z, not just nb·B rows: derivative callers block over the
        # ghost-stripped output (nb·B < nx), and their LAST block's
        # bottom edge must come from the real trailing ghost rows — the
        # extra virtual block supplies exactly those before [:nb] trims.
        nb2 = max(nb, -(-nx // B))
        zp = (z if nb2 * B == nx
              else jnp.pad(z, ((0, nb2 * B - nx), (0, 0))))
        zr = zp.reshape(nb2, B, ny)
        top = jnp.roll(zr[:, B - G:], 1, axis=0)[:nb]
        bot = jnp.roll(zr[:, :G], -1, axis=0)[:nb]
        # poison the rolled-in out-of-range rows (see docstring)
        top = top.at[0].set(0.0)
        if nb == nb2:  # trimming exposed the wrapped last bottom edge
            bot = bot.at[nb - 1].set(0.0)
        return top, bot

    def strided(src, width):
        # blocks of `width` rows at stride B over `src`:
        # result[i, j] = src[i·B + j]
        s = jnp.pad(src, ((0, max(total - src.shape[0], 0)), (0, 0)))[:total]
        return s.reshape(nb, B, ny)[:, :width]

    # wide edges (G > B — reachable only through the test-hook tile
    # clamps) in ⌈G/B⌉ strided chunks; position q of the shifted top
    # source must hold z[q−G] for EVERY q with 0 ≤ q−G < nx — including
    # q ≥ nx (blocks whose padded position passes the array end while the
    # source row still exists), so the shift prepends G rows rather than
    # truncating the tail
    z_top = jnp.concatenate([z[:G], z], axis=0)  # [q] = z[q − G]
    tops, bots = [], []
    for c0 in range(0, G, B):
        w = min(B, G - c0)
        tops.append(strided(z_top[c0:], w))
        bots.append(strided(z[min(B + c0, nx):], w))
    top = tops[0] if len(tops) == 1 else jnp.concatenate(tops, axis=1)
    bot = bots[0] if len(bots) == 1 else jnp.concatenate(bots, axis=1)
    # poison every top row whose source precedes z (top[i, j] sources row
    # i·B − G + j, negative for any block with i·B < G — the z[:G]
    # prepend is filler there; bots' pad already zeroes their
    # out-of-range tail)
    src_row = (
        jnp.arange(nb)[:, None] * B - G + jnp.arange(G)[None, :]
    )
    top = jnp.where(src_row[:, :, None] >= 0, top, 0.0)
    return top, bot


def _heat_stream0_kernel(z_ref, top_ref, bot_ref, coef_ref, out_ref, *,
                         steps, B, G, R, border_coeff=False):
    """Row-streaming 2-D heat (5-point Laplacian) k-step block: per step,
    ``interior += cx·δ²x + cy·δ²y`` over the maximal span — the exact
    recurrence of ``heat_step2d_fn``'s XLA body (stale creep within the
    ghost band included), so the two tiers are update-for-update
    identical. Column taps stay in-window (full shard width rides in the
    block); row windows carry G-row gathered edges, and a row at edge
    distance d is correct through step d, so G ≥ steps makes the output
    block's influence cone exact (same argument as the 1-D iterate).

    Formulation note (Mosaic constraints): the update is computed at EVERY
    window position from full-extent shifted copies (row shifts are
    full-lane-width concats along the sublane dim, col shifts concats
    along the lane dim — both legal; a col-sliced interior stitch is not,
    because `tpu.concatenate` rejects lane-offset mismatches on non-concat
    dims, and `dynamic_update_slice` has no TPU lowering at all), then
    border/ghost positions keep their old value via one precomputed
    2-D mask — scalar row bounds fold the per-block absolute-row clip, so
    no per-block branch is needed."""
    cx = coef_ref[0]
    cy = coef_ref[1]
    i = pl.program_id(0)
    window = jnp.concatenate([top_ref[0], z_ref[:], bot_ref[0]], axis=0)
    W = window.shape[0]
    ny = window.shape[1]
    abs0 = i * B - G  # absolute shard row of window position 0
    w_iota = jax.lax.broadcasted_iota(jnp.int32, (W, ny), 0)
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (W, ny), 1)
    lo_r = jnp.maximum(1, 1 - abs0)          # window-pos row bounds with
    hi_r = jnp.minimum(W - 1, R - 1 - abs0)  # the absolute clip folded in
    ok = ((w_iota >= lo_r) & (w_iota < hi_r)
          & (c_iota >= 1) & (c_iota < ny - 1))
    if border_coeff:
        # border handling via once-precomputed ZEROED coefficient arrays
        # instead of a per-step select: w + 0·δ²x + 0·δ²y == w exactly
        # (finite fields), so border/ghost positions keep their value
        # bit-identically while each step drops the where — ~1 of the
        # body's ~11 VPU ops (round-5 A/B). The finiteness premise needs
        # one sanitization: a ragged last block's z-rows beyond the array
        # (abs row ≥ R) are pallas pad junk — NaN-poisoned in interpret
        # mode, arbitrary bits on hardware — which the where-path never
        # lets into arithmetic but 0·junk would (0·NaN = NaN). Zero them
        # once per call; their outputs are discarded out-of-bounds
        # writes, so the zeroing is unobservable.
        zero = jnp.zeros((), window.dtype)
        window = jnp.where(w_iota + abs0 < R, window, zero)
        if jnp.dtype(window.dtype).itemsize < 4:
            # sub-f32 only: an i1 mask against bf16 scalar broadcasts
            # trips a Mosaic relayout ("Non-singleton logical dimension
            # is replicated ... (8,128) -> (16,128)"); f32 select +
            # downcast lowers cleanly and the bf16(f32(cx)) round trip
            # is exact. f32/f64 select natively — routing them through
            # f32 would silently round f64 coefficients.
            cxa = jnp.where(
                ok, jnp.float32(cx), jnp.float32(0.0)
            ).astype(window.dtype)
            cya = jnp.where(
                ok, jnp.float32(cy), jnp.float32(0.0)
            ).astype(window.dtype)
        else:
            cxa = jnp.where(ok, cx, zero)
            cya = jnp.where(ok, cy, zero)
    for _ in range(steps):
        up = jnp.concatenate([window[1:W], window[W - 1:W]], axis=0)
        down = jnp.concatenate([window[0:1], window[0:W - 1]], axis=0)
        right = jnp.concatenate(
            [window[:, 1:ny], window[:, ny - 1:ny]], axis=1
        )
        left = jnp.concatenate(
            [window[:, 0:1], window[:, 0:ny - 1]], axis=1
        )
        if border_coeff:
            window = (window + cxa * (up + down - 2.0 * window)
                      + cya * (left + right - 2.0 * window))
        else:
            new = (window + cx * (up + down - 2.0 * window)
                   + cy * (left + right - 2.0 * window))
            window = jnp.where(ok, new, window)
    out_ref[:] = jax.lax.slice_in_dim(window, G, G + B, axis=0)


@functools.partial(
    jax.jit, static_argnames=("steps", "n_bnd", "interpret", "tile_rows",
                              "border_coeff"),
    donate_argnums=0,
)
def heat2d_pallas(z, cx, cy, steps: int = 1, n_bnd: int = 1,
                  interpret: bool | None = None,
                  tile_rows: int | None = None,
                  border_coeff: bool = False):
    """Hand tier of the heat mini-app's update (``heat_step2d_fn``):
    ``steps`` explicit-Euler 5-point-Laplacian steps on a both-dims-ghosted
    shard, in place (aliased), 2 HBM passes per call vs the XLA body's ~6
    per step. Full shard width rides in each block (column ghosts are
    in-window); rows stream with gathered G-row edges, so height is
    unbounded. Raises when the width alone exceeds the VMEM budget (the
    XLA body is the fallback there).

    ``border_coeff=True`` (round-5 opt-in): replaces the per-step border
    ``where`` with once-precomputed zeroed coefficient arrays —
    bit-identical to the default path for FINITE fields without signed
    zeros at preserved positions (``w + 0·δ`` keeps ``w`` exactly;
    a −0.0 border cell can flip to +0.0, and an inf/NaN border cell
    becomes NaN — the where path preserves both bit-exactly). Measured
    flat-to-marginally-faster by min-estimator (0.875–0.984 across
    tall-domain A/B rounds) but within the contention band by median, so
    the default stays the where path; the fit charges the variant's two
    extra window-sized arrays (``extra_temps``), shrinking B instead of
    risking a scoped-vmem OOM at budget-edge widths. BASELINE round-5
    heat note."""
    nx, ny = z.shape
    G = n_bnd
    if steps > G:
        raise ValueError(f"heat2d_pallas: steps={steps} > ghost width {G}")
    # NOTE a round-4 attempt to clamp bf16 blocks at 128 on A/B evidence
    # was REVERTED: at widths where B=256 genuinely fits (≤~2.5k bf16)
    # the 2048² workload sits under the ~100 µs per-call overhead floor
    # and the measured "difference" was noise, while at 4096² the
    # calibrated fit caps B at 128 anyway — both A/B arms had silently
    # run the same kernel. The fitted B stands; tile_rows remains the
    # explicit override.
    itemsize_z = jnp.dtype(z.dtype).itemsize
    B = _stream_fit(
        z, G, "heat2d_pallas", tile_rows,
        bf16_temps=(_BF16_TEMPS_HEAT
                    if jnp.dtype(z.dtype) == jnp.bfloat16
                    else _BF16_TEMPS_DEFAULT),
        # the border_coeff variant keeps 2 window-sized coefficient
        # arrays live beyond the calibrated mix — charge them so the
        # fit shrinks B instead of scoped-OOMing at budget-edge widths
        extra_temps=(2.0 * itemsize_z if border_coeff else 0.0),
    )
    nb = pl.cdiv(nx, B)
    top, bot = _row_block_edges(z, B, G, nb)
    coef = jnp.asarray([cx, cy], z.dtype)
    return pl.pallas_call(
        functools.partial(
            _heat_stream0_kernel, steps=steps, B=B, G=G, R=nx,
            border_coeff=border_coeff,
        ),
        out_shape=jax.ShapeDtypeStruct((nx, ny), z.dtype),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((B, ny), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, G, ny), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, G, ny), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((B, ny), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        input_output_aliases={0: 0},
        interpret=_auto_interpret(interpret),
    )(z, top, bot, coef)


def _dual_step_kernel(z_ref, bot_ref, coef_ref, dx_ref, dy_ref, res_ref, *,
                      B, G, mx, lean):
    """One streamed (B, ny) block of the flagship dual-dim pipeline
    (``dual_dim_step``): dz/dx (row taps on the col interior), dz/dy
    (lane taps on the row interior), and this block's residual partial —
    three outputs from ONE read of the window, vs the XLA tier's
    per-tap re-reads. Ragged last-block rows are excluded from the
    residual by an absolute-row mask (their derivative rows are dropped
    by the pipeline's ragged store masking).

    ``lean`` (round-5 op diet, measured on chip — BASELINE round-5
    dual-dim note): difference-form taps (STENCIL5 is antisymmetric,
    asserted at module load) with the per-axis scale folded into the two
    coefficients — 5 vector ops/axis vs the raw accumulation's 8 — and
    ONE fused masked residual reduction (1 where + 1 sum vs 2 + 2). The
    fold happens on the f32 SCALAR unit (bf16 scalar arith does not
    legalize; the converts do), so only the final coefficient cast pays
    16-bit rounding. Values differ from the raw form only by FP
    association; the drivers' analytic gates cover both."""
    sx = coef_ref[0]
    sy = coef_ref[1]
    i = pl.program_id(0)
    window = jnp.concatenate([z_ref[:], bot_ref[0]], axis=0)  # (B+2G, ny)
    ny = window.shape[1]
    my = ny - 2 * G
    core = window[:, G:ny - G]
    mid = jax.lax.slice_in_dim(window, G, G + B, axis=0)
    if lean:
        dt = window.dtype
        sxf = sx.astype(jnp.float32)
        syf = sy.astype(jnp.float32)
        c1x = (sxf * _C1).astype(dt)
        c2x = (sxf * _C2).astype(dt)
        c1y = (syf * _C1).astype(dt)
        c2y = (syf * _C2).astype(dt)

        def rs(off):
            return jax.lax.slice_in_dim(core, G + off, G + off + B,
                                        axis=0)

        def cs(off):
            return jax.lax.slice_in_dim(mid, G + off, G + off + my,
                                        axis=1)

        dx = c1x * (rs(1) - rs(-1)) + c2x * (rs(2) - rs(-2))
        dy = c1y * (cs(1) - cs(-1)) + c2y * (cs(2) - cs(-2))
    else:
        taps = [(k, c) for k, c in enumerate(STENCIL5.tolist())
                if c != 0.0]
        accx = None
        for k, c in taps:
            t = c * jax.lax.slice_in_dim(core, k, k + B, axis=0)
            accx = t if accx is None else accx + t
        dx = accx * sx
        accy = None
        for k, c in taps:
            t = c * jax.lax.slice_in_dim(mid, k, k + my, axis=1)
            accy = t if accy is None else accy + t
        dy = accy * sy
    dx_ref[:] = dx
    dy_ref[:] = dy
    valid = (jax.lax.broadcasted_iota(jnp.int32, dx.shape, 0) + i * B) < mx
    # residual accumulates in f32: Mosaic cannot legalize the bf16
    # cross-lane reduction (round-4 vmemprobe coverage extension caught
    # 'failed to legalize arith.addf' — this kernel had only ever been
    # compiled at f32), and f32 accumulation of squares is the right
    # numerics at 16-bit anyway
    dxf = dx.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    if lean:
        r = jnp.sum(jnp.where(valid, dxf * dxf + dyf * dyf, zero))
    else:
        r = (jnp.sum(jnp.where(valid, dxf * dxf, zero))
             + jnp.sum(jnp.where(valid, dyf * dyf, zero)))
    # broadcast the partial over a full (8, 128) register tile (hardware
    # Mosaic requires output blocks to be whole sublane×lane tiles; a
    # per-block scalar store would need SMEM plumbing) — summing r/1024
    # over the 1024 tile slots reproduces r to rounding
    # the scalar divide stays f32 too (bf16 arith.divf does not
    # legalize either); only the final store casts to the array dtype
    res_ref[:] = jnp.full((8, 128), r / 1024.0, jnp.float32).astype(
        dx.dtype
    )


# Lean (op-diet) body default per dtype, measured on chip (BASELINE
# round-5 dual-dim op-diet note): the lean body was built because the
# bf16 tier measured ISSUE-bound (0.585-0.606x its bytes ceiling with
# ops axis ~= bytes axis), so fewer nominal vector ops should have
# converted to wall-clock. The interleaved per-size A/B REFUTED it:
# raw/lean marginal = 0.75x f32, 0.915x bf16 (lean slower at both
# dtypes), and the in-VMEM probes explain why — the raw 4-tap body's
# const-mul+add pairs execute as FMAs (f32 95 vs lean 69 G elem/s
# resident), so the difference-form sub/mul/add chain is MORE real VPU
# work despite fewer nominal ops. The raw body is measured-best; lean
# stays an exactness-gated opt-in (`lean=True`) and
# tests/test_pallas.py pins this table to the measured verdict.
_DUAL_DIM_LEAN_DEFAULT = {"float32": False, "bfloat16": False}


@functools.partial(
    jax.jit, static_argnames=("n_bnd", "interpret", "tile_rows", "lean"),
)
def dual_dim_step_pallas(z, n_bnd: int, scale_x: float, scale_y: float,
                         interpret: bool | None = None,
                         tile_rows: int | None = None,
                         lean: bool | None = None):
    """Hand tier of :func:`~tpu_mpi_tests.kernels.stencil.dual_dim_step`
    (the 2-D process-grid step's per-shard pipeline): row-streamed blocks
    produce both derivatives and the residual from one window read.
    Same contract: ``(dz_dx, dz_dy, residual)`` with the ghost frame
    stripped. Raises the shared "VMEM budget" error when the width alone
    cannot fit (callers fall back to the XLA tier).

    ``lean`` selects the op-diet kernel body (see ``_dual_step_kernel``);
    ``None`` resolves through the measured-best per-dtype table
    ``_DUAL_DIM_LEAN_DEFAULT``."""
    from tpu_mpi_tests.kernels.stencil import N_BND as RADIUS_BND

    if n_bnd != RADIUS_BND:
        raise ValueError(
            f"dual_dim_step_pallas requires n_bnd == {RADIUS_BND}, "
            f"got {n_bnd}"
        )
    nx, ny = z.shape
    G = n_bnd
    if min(nx, ny) < 2 * G + 1:
        raise ValueError(
            f"dual_dim_step_pallas: both dims need >= {2 * G + 1} points "
            f"(2·n_bnd ghosts + interior), got {z.shape}"
        )
    mx, my = nx - 2 * G, ny - 2 * G
    if lean is None:
        lean = _DUAL_DIM_LEAN_DEFAULT.get(jnp.dtype(z.dtype).name, False)
    B = _stream_fit(
        z, G, "dual_dim_step_pallas", tile_rows,
        bf16_temps=(_BF16_TEMPS_DUAL_DIM
                    if jnp.dtype(z.dtype) == jnp.bfloat16
                    else _BF16_TEMPS_DEFAULT),
    )
    nb = pl.cdiv(mx, B)
    _, bot = _row_block_edges(z, B, 2 * G, nb)
    coef = jnp.asarray([scale_x, scale_y], z.dtype)
    dx, dy, res = pl.pallas_call(
        functools.partial(_dual_step_kernel, B=B, G=G, mx=mx, lean=lean),
        out_shape=(
            jax.ShapeDtypeStruct((mx, my), z.dtype),
            jax.ShapeDtypeStruct((mx, my), z.dtype),
            jax.ShapeDtypeStruct((nb * 8, 128), z.dtype),
        ),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((B, ny), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2 * G, ny), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((B, my), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, my), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 128), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=_auto_interpret(interpret),
    )(z, bot, coef)
    return dx, dy, jnp.sum(res)


# ---------------------------------------------------------------------------
# Ring halo exchange over ICI (inter-chip RDMA)
# ---------------------------------------------------------------------------


def _ring_edge_kernel(cur_lo_ref, cur_hi_ref, lo_edge_ref, hi_edge_ref,
                      new_lo_ref, new_hi_ref, send_sem, recv_sem,
                      *, axis_name, periodic, use_barrier, symmetric):
    """Pure-communication ring kernel: bidirectional neighbor exchange of
    edge blocks with explicit remote DMA (≅ the ``MPI_Irecv``/``Isend``/
    ``Waitall`` body of ``boundary_exchange``, ``mpi_stencil_gt.cc:96-121``:
    post both directions, overlap, wait).

    Operands are the small edge/ghost arrays only — the shard itself never
    enters the kernel (Mosaic DMA slices must be tile-aligned, which
    ``n_bnd``-wide rows/columns of a ghosted shard never are, so the
    alignment-free XLA slice/update does the pack/unpack while this kernel
    owns the wire). Full-ref DMA of whole operands needs no slicing, so any
    shape/dtype works and traffic is 2·n_bnd·extent per call, independent
    of shard size.

    ``new_lo``/``new_hi`` are ALIASED to ``cur_lo``/``cur_hi`` (the current
    ghost contents): ranks that receive nothing — non-periodic ring edges,
    ≅ the reference's ``rank > 0`` / ``rank < world-1`` guards
    (``mpi_stencil_gt.cc:96-107``) — hand back their physical ghosts
    untouched, so the caller writes results back unconditionally.

    ``symmetric=True`` (bool-interpret mode only) sends unconditionally,
    wrap-around included: that interpreter emulates remote DMA with XLA
    collectives, so a conditional send is a conditional collective — a
    rendezvous deadlock when edge ranks skip it. The wrapper restores
    physical ghosts after. The threaded ``InterpretParams`` simulator has
    real per-device sends, so it runs the hardware path (conditional
    sends + barrier) unchanged.
    """
    del cur_lo_ref, cur_hi_ref  # alias donors; their data is already in new_*
    n_dev = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    # idx is int32; keep the modulus int32 too (x64 would promote the int)
    right = jax.lax.rem(idx + 1, jnp.int32(n_dev))
    left = jax.lax.rem(idx - 1 + jnp.int32(n_dev), jnp.int32(n_dev))

    if use_barrier:
        # neighborhood barrier: both neighbors have entered this call, so
        # their output buffers are live and last call's reads are done
        # (guide pattern; protects chained iterations). Compiled out only
        # under the serializing bool interpreter (remote signals
        # unimplemented there); the threaded InterpretParams simulator
        # runs it for real.
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    # my hi interior edge → right neighbor's lo ghost (slot 0)
    rdma_hi = pltpu.make_async_remote_copy(
        src_ref=hi_edge_ref,
        dst_ref=new_lo_ref,
        send_sem=send_sem.at[0],
        recv_sem=recv_sem.at[0],
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    # my lo interior edge → left neighbor's hi ghost (slot 1)
    rdma_lo = pltpu.make_async_remote_copy(
        src_ref=lo_edge_ref,
        dst_ref=new_hi_ref,
        send_sem=send_sem.at[1],
        recv_sem=recv_sem.at[1],
        device_id=left,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    if symmetric:
        rdma_hi.start()
        rdma_lo.start()
        rdma_hi.wait()
        rdma_lo.wait()
        return

    # send-right pair: I send iff I have a right neighbor; the matching
    # arrival (into my lo ghost) happens iff I have a left neighbor
    send_hi_ok = jnp.logical_or(bool(periodic), idx < n_dev - 1)
    send_lo_ok = jnp.logical_or(bool(periodic), idx > 0)

    @pl.when(send_hi_ok)
    def _():
        rdma_hi.start()

    @pl.when(send_lo_ok)
    def _():
        rdma_lo.start()

    @pl.when(send_hi_ok)
    def _():
        rdma_hi.wait_send()

    @pl.when(send_lo_ok)
    def _():
        rdma_lo.wait_send()

    # recv waits mirror the neighbor's send predicates exactly
    @pl.when(send_lo_ok)
    def _():
        rdma_hi.wait_recv()  # left's hi edge landed in my lo ghost

    @pl.when(send_hi_ok)
    def _():
        rdma_lo.wait_recv()  # right's lo edge landed in my hi ghost


def ring_halo_pallas(
    z,
    *,
    axis_name: str,
    axis: int = 0,
    n_bnd: int = N_BND,
    periodic: bool = False,
    collective_id: int = 7,
    interpret: bool | None = None,
):
    """Per-shard halo exchange with explicit inter-chip RDMA — the
    hand-tuned analog of ``exchange_shard``'s ``ppermute`` (SURVEY.md §5.8:
    ≅ the manual CUDA-aware-MPI path). Call *inside* ``shard_map``
    over ``axis_name``; ghost regions along ``axis`` are filled from ring
    neighbors, physical ghosts kept on non-periodic edges.

    The shard never enters the kernel: XLA slices the two ``n_bnd``-wide
    interior edges (edge-proportional traffic), the pallas kernel moves them
    over ICI with explicit remote DMA, and XLA splices the received blocks
    into the ghost regions. Works at reference scale (1028×512Ki ≈ 2.1 GB
    shards) where a whole-shard VMEM formulation cannot, and at any
    alignment — Mosaic tile-alignment rules apply only to sliced DMA, and
    this kernel only ever DMAs full refs."""
    if z.ndim == 1:
        # 1-D ring (stencil1d): run as an (n, 1) column
        out = ring_halo_pallas(
            z.reshape(-1, 1),
            axis_name=axis_name,
            axis=0,
            n_bnd=n_bnd,
            periodic=periodic,
            collective_id=collective_id,
            interpret=interpret,
        )
        return out.reshape(-1)
    interp = _auto_interpret(interpret)
    serial = _serial_interpret(interp)
    size = z.shape[axis]
    cur_lo = jax.lax.slice_in_dim(z, 0, n_bnd, axis=axis)
    cur_hi = jax.lax.slice_in_dim(z, size - n_bnd, size, axis=axis)
    lo_edge = jax.lax.slice_in_dim(z, n_bnd, 2 * n_bnd, axis=axis)
    hi_edge = jax.lax.slice_in_dim(
        z, size - 2 * n_bnd, size - n_bnd, axis=axis
    )
    edge_struct = jax.ShapeDtypeStruct(cur_lo.shape, z.dtype)
    new_lo, new_hi = pl.pallas_call(
        functools.partial(
            _ring_edge_kernel,
            axis_name=axis_name,
            periodic=periodic,
            use_barrier=not serial,
            symmetric=serial,
        ),
        out_shape=(edge_struct, edge_struct),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases={0: 0, 1: 1},
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(cur_lo, cur_hi, lo_edge, hi_edge)
    if serial and not periodic:
        # symmetric interpret mode sent the wrap-around pair too; put the
        # physical ghosts back on the ring-edge ranks
        n_dev = axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        new_lo = jnp.where(idx == 0, cur_lo, new_lo)
        new_hi = jnp.where(idx == n_dev - 1, cur_hi, new_hi)
    out = jax.lax.dynamic_update_slice_in_dim(z, new_lo, 0, axis=axis)
    return jax.lax.dynamic_update_slice_in_dim(
        out, new_hi, size - n_bnd, axis=axis
    )


def _patch_rows(window, start, rows, use):
    """Replace ``window[start:start+len(rows)]`` with ``rows`` when the
    scalar predicate ``use`` holds (traced or static) — the fused ring
    kernel's ghost-band substitution, stitched with the same concat idiom
    as ``_masked_step`` so the surviving cells' arithmetic is untouched."""
    n = rows.shape[0]
    seg = jax.lax.slice_in_dim(window, start, start + n, axis=0)
    seg = jnp.where(use, rows.astype(window.dtype), seg)
    W = window.shape[0]
    return jnp.concatenate(
        [
            jax.lax.slice_in_dim(window, 0, start, axis=0),
            seg,
            jax.lax.slice_in_dim(window, start + n, W, axis=0),
        ],
        axis=0,
    )


def _fused_rdma_kernel(z_ref, top_ref, bot_ref, cur_lo_ref, cur_hi_ref,
                       lo_edge_ref, hi_edge_ref, scale_eps_ref, *rest,
                       axis_name, steps, B, K, R, nb, i_lo_mask, i_hi_mask,
                       periodic, use_barrier, symmetric, phys_static,
                       local_only, seam_wait):
    """ONE-launch fused halo+stencil step (ISSUE 15 tentpole): in-kernel
    RDMA of the edge bands overlapped with the interior k-step update.

    Grid step ``i`` processes row block ``blk = (i + 1) % nb`` — the
    permutation puts the two EDGE blocks (nb−1, then 0) last, so the
    schedule is:

    * step 0: neighborhood barrier, then ``make_async_remote_copy`` of
      both interior edge bands to the ring neighbors (my hi edge → right
      neighbor's lo ghost, my lo edge → left's hi ghost) — the
      ``MPI_Irecv``/``Isend`` post of ``mpi_stencil2d_sycl.cc``'s manual
      pipeline, issued before any compute;
    * steps 0..nb−3: interior row blocks advance ``steps`` timesteps
      from OLD data (windows assembled from the pre-sliced neighbor-edge
      operands — cells touching no fresh ghost, the PR-7 CORE split
      moved device-side) while the DMAs fly;
    * step nb−2: wait on the recv semaphores (the seam point), copy the
      landed ghost bands to VMEM, then finish the two seam blocks —
      block nb−1 here, block 0 at step nb−1 — with their ghost rows
      patched from the arrivals (``_patch_rows``) and the same masked
      advance the streaming kernel uses, so fused interiors are
      BITWISE-identical to the chained exchange→kernel path.

    ``local_only=True`` compiles the communication out entirely (no
    barrier, no sends, no waits, no patches): the pure compute pass a
    1-shard non-periodic ring degenerates to, and the host-bracketed
    baseline the seam-wait ``overlap_frac`` probe times against.

    Non-receiving sides (non-periodic ring edges) keep their physical
    ghosts: the patch predicate is ``~phys``, so the window's own (old,
    physical) ghost rows survive — which also neutralizes the symmetric
    bool-interpret mode's wrap-around arrivals, the same fix-up
    ``ring_halo_pallas`` does outside the kernel.
    """
    if phys_static is None:
        phys_ref = rest[0]
        rest = rest[1:]
        phys_lo = phys_ref[0] != 0
        phys_hi = phys_ref[1] != 0
    else:
        phys_lo, phys_hi = bool(phys_static[0]), bool(phys_static[1])
    (out_ref, new_lo_ref, new_hi_ref,
     lo_scr, hi_scr, copy_sem, send_sem, recv_sem) = rest
    del cur_lo_ref, cur_hi_ref  # alias donors; their data is in new_*
    se = scale_eps_ref[0]
    i = pl.program_id(0)
    blk = jax.lax.rem(i + 1, jnp.int32(nb))
    # the seam point: first edge block (nb−1) runs at grid step nb−2
    # (nb == 1: the only block is both edges, everything at step 0)
    wait_step = max(nb - 2, 0)

    if not local_only:
        n_dev = axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(idx + 1, jnp.int32(n_dev))
        left = jax.lax.rem(idx - 1 + jnp.int32(n_dev), jnp.int32(n_dev))
        # my hi interior edge → right neighbor's lo ghost (slot 0);
        # my lo interior edge → left neighbor's hi ghost (slot 1)
        rdma_hi = pltpu.make_async_remote_copy(
            src_ref=hi_edge_ref,
            dst_ref=new_lo_ref,
            send_sem=send_sem.at[0],
            recv_sem=recv_sem.at[0],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma_lo = pltpu.make_async_remote_copy(
            src_ref=lo_edge_ref,
            dst_ref=new_hi_ref,
            send_sem=send_sem.at[1],
            recv_sem=recv_sem.at[1],
            device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        send_hi_ok = jnp.logical_or(bool(periodic), idx < n_dev - 1)
        send_lo_ok = jnp.logical_or(bool(periodic), idx > 0)
        first = i == 0

        if use_barrier:
            # both neighbors entered this call: their landing buffers are
            # live and last call's reads are done (ring_halo_pallas's
            # chained-iteration protection, unchanged)
            @pl.when(first)
            def _():
                barrier = pltpu.get_barrier_semaphore()
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_wait(barrier, 2)

        if symmetric:
            # serializing bool interpreter: remote DMA is emulated with
            # XLA collectives, so a conditional send is a conditional
            # collective — send unconditionally and wait in place; the
            # ~phys patch predicate below discards wrap-around arrivals
            # on non-periodic edge ranks (ring_halo_pallas's fix-up,
            # done in-window)
            @pl.when(first)
            def _():
                rdma_hi.start()
                rdma_lo.start()
                rdma_hi.wait()
                rdma_lo.wait()
        else:
            @pl.when(first & send_hi_ok)
            def _():
                rdma_hi.start()

            @pl.when(first & send_lo_ok)
            def _():
                rdma_lo.start()

            if seam_wait:
                # recv waits mirror the neighbor's send predicates: my lo
                # ghost lands iff I have a left neighbor, etc. — and they
                # are the happens-before edge the vector-clock race test
                # asserts (tests/test_ring_sync.py); ``seam_wait=False``
                # (the unsafe negative control) removes exactly this edge
                @pl.when((i == wait_step) & send_lo_ok)
                def _():
                    rdma_hi.wait_recv()  # left's hi edge → my lo ghost

                @pl.when((i == wait_step) & send_hi_ok)
                def _():
                    rdma_lo.wait_recv()  # right's lo edge → my hi ghost

            @pl.when((i == nb - 1) & send_hi_ok)
            def _():
                rdma_hi.wait_send()

            @pl.when((i == nb - 1) & send_lo_ok)
            def _():
                rdma_lo.wait_send()

        @pl.when(i == wait_step)
        def _():
            # landed ghost bands → VMEM for the seam windows (full-ref
            # copies, so no tile-alignment constraint on K)
            cp_lo = pltpu.make_async_copy(new_lo_ref, lo_scr,
                                          copy_sem.at[0])
            cp_hi = pltpu.make_async_copy(new_hi_ref, hi_scr,
                                          copy_sem.at[1])
            cp_lo.start()
            cp_hi.start()
            cp_lo.wait()
            cp_hi.wait()

    window = jnp.concatenate([top_ref[0], z_ref[:], bot_ref[0]], axis=0)
    if not local_only:
        # edge blocks read the ARRIVED ghosts; physical sides keep the
        # window's own (old) ghost rows — which is also what neutralizes
        # the symmetric-mode wrap-around arrivals
        use_lo = jnp.logical_and(blk == 0, jnp.logical_not(phys_lo))
        use_hi = jnp.logical_and(blk == jnp.int32(nb - 1),
                                 jnp.logical_not(phys_hi))
        window = _patch_rows(window, K, lo_scr[:], use_lo)
        window = _patch_rows(window, B, hi_scr[:], use_hi)

    abs0 = blk * B - K  # absolute (ghosted) row of window position 0

    # the SHARED k-step advance (_kstep_advance — one implementation
    # with the streaming kernel is what makes the fused-vs-chained
    # interiors bitwise-identical by construction)
    advance = functools.partial(
        _kstep_advance, steps=steps, K=K, R=R, abs0=abs0, se=se,
        phys_lo=phys_lo, phys_hi=phys_hi, phys_static=phys_static,
    )
    needs_mask = (blk < i_lo_mask) | (blk >= i_hi_mask)
    window = jax.lax.cond(
        needs_mask,
        functools.partial(advance, masked=True),
        functools.partial(advance, masked=False),
        window,
    )
    out_ref[:] = jax.lax.slice_in_dim(window, K, K + B, axis=0)


def stencil2d_fused_rdma_pallas(
    z,
    scale_eps,
    *,
    axis_name: str,
    steps: int = 1,
    periodic: bool = False,
    phys=None,
    phys_static: "tuple[int, int] | None" = None,
    collective_id: int = 12,
    interpret: bool | None = None,
    tile_rows: int | None = None,
    local_only: bool = False,
    unsafe_no_seam_wait: bool = False,
):
    """One-launch fused halo-exchange + k-step stencil update along dim 0
    (ISSUE 15): a single ``pl.pallas_call`` kicks off the RDMA of both
    edge bands, streams the interior row blocks while the DMA is in
    flight, then waits on the recv semaphores and finishes the seam
    blocks — see :func:`_fused_rdma_kernel` for the device schedule.
    Call *inside* ``shard_map`` over ``axis_name``; semantics (deep
    ghosts, ``phys``/``phys_static`` flags, shape preservation, input
    aliasing) match ``ring_halo_pallas`` + ``stencil2d_iterate_pallas``
    chained, with interiors bitwise-identical to that chain (tested).

    Like ``ring_halo_pallas``, the pack/unpack stays alignment-free: XLA
    pre-slices the four edge/ghost bands (full-ref RDMA only), and the
    compute operand streams through BLOCKED specs (no manual sliced DMA).
    Row blocks must divide the ghosted height and hold the full seam
    (``B >= 2K`` — a non-edge block's window must never reach a ghost
    band, or it would read stale values mid-stream); domains whose width
    exceeds the VMEM budget raise the same "VMEM budget" ValueError as
    the other streaming kernels.

    ``local_only=True`` (or a 1-shard non-periodic ring, which the
    runner lowers to it) compiles every communication op out — the pure
    compute pass, and the baseline the seam-wait probe times against.
    ``unsafe_no_seam_wait`` removes the recv waits (the seam-read /
    ghost-arrival synchronization edge) for the race-detector negative
    control only."""
    if z.ndim != 2:
        raise ValueError("stencil2d_fused_rdma_pallas: 2-D shards only")
    interp = _auto_interpret(interpret)
    serial = _serial_interpret(interp)
    R, Wn = z.shape
    K = steps * N_BND
    if R <= 2 * K:
        raise ValueError(
            f"height {R} too small for {steps}-step ghost width {2 * K}"
        )
    itemsize = jnp.dtype(z.dtype).itemsize
    sub = max(8, 8 * 4 // itemsize)
    bf16_temps = (_BF16_TEMPS_ITER_STREAM
                  if jnp.dtype(z.dtype) == jnp.bfloat16
                  else _BF16_TEMPS_DEFAULT)
    B = _fit_block_rows(Wn, K, itemsize, sub, bf16_temps)
    # the two (K, W) ghost-landing scratch buffers live alongside the
    # streaming window — charge them against the same budget
    scr_bytes = 2 * K * Wn * itemsize
    while B > sub and _stream_live_bytes(B, K, Wn, itemsize,
                                         bf16_temps) + scr_bytes > \
            _VMEM_BUDGET_CAL:
        B = max(sub, (B // 2) // sub * sub)
    if _stream_live_bytes(B, K, Wn, itemsize, bf16_temps) + scr_bytes > \
            _VMEM_BUDGET_CAL:
        raise ValueError(
            f"stencil2d_fused_rdma_pallas: width {Wn} exceeds the VMEM "
            f"budget even at {B}-row blocks; use the XLA tier"
        )
    if tile_rows is not None:
        _validate_tile_rows(tile_rows, sub)
        B = min(B, tile_rows)
    # blocks must tile the ghosted height exactly (the edge blocks' ghost
    # rows sit at static window offsets) and hold a FULL seam: B >= 2K
    # keeps every non-edge block's window out of the ghost bands — the
    # core/seam split is per-block, so a window that straddled a ghost
    # band from an interior block would read stale values mid-stream
    B = _fit_divisor(R, B)
    if B < 2 * K:
        raise ValueError(
            f"stencil2d_fused_rdma_pallas: no row blocking of height {R} "
            f"holds the {2 * K}-row seam (largest fitting divisor {B}); "
            f"pad the domain or use another tier"
        )
    nb = R // B
    # per-block static masking classification (stream0's): block b is
    # mask-free iff its window stays inside the worst-case update bands
    i_lo_mask = -(-(2 * K - N_BND) // B)
    i_hi_mask = (R - B - 2 * K + N_BND) // B + 1
    top, bot = _row_block_edges(z, B, K, nb)
    cur_lo = jax.lax.slice_in_dim(z, 0, K, axis=0)
    cur_hi = jax.lax.slice_in_dim(z, R - K, R, axis=0)
    lo_edge = jax.lax.slice_in_dim(z, K, 2 * K, axis=0)
    hi_edge = jax.lax.slice_in_dim(z, R - 2 * K, R - K, axis=0)
    se = jnp.asarray(scale_eps, z.dtype).reshape(1)
    if phys is None and phys_static is None:
        phys_static = (0, 0)  # both sides exchange-fed

    def blkmap(i):
        return (jax.lax.rem(i + 1, jnp.int32(nb)), 0)

    in_specs = [
        pl.BlockSpec((B, Wn), blkmap, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, K, Wn), lambda i: (*blkmap(i), 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, K, Wn), lambda i: (*blkmap(i), 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [z, top, bot, cur_lo, cur_hi, lo_edge, hi_edge, se]
    if phys_static is None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.asarray(phys, jnp.int32).reshape(2))
    edge_struct = jax.ShapeDtypeStruct((K, Wn), z.dtype)
    out, _, _ = pl.pallas_call(
        functools.partial(
            _fused_rdma_kernel,
            axis_name=axis_name,
            steps=steps,
            B=B,
            K=K,
            R=R,
            nb=nb,
            i_lo_mask=i_lo_mask,
            i_hi_mask=i_hi_mask,
            periodic=periodic,
            use_barrier=not serial and not local_only,
            symmetric=serial,
            phys_static=phys_static,
            local_only=local_only,
            seam_wait=not unsafe_no_seam_wait,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((R, Wn), z.dtype),
            edge_struct,
            edge_struct,
        ),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((B, Wn), blkmap, memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((K, Wn), z.dtype),
            pltpu.VMEM((K, Wn), z.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases={0: 0, 3: 1, 4: 2},
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(*operands)
    return out


def _ring_allgather_kernel(x_ref, out_ref, copy_sem, send_sem, recv_sem,
                           *, axis_name, use_barrier, loopback_w=None):
    """Ring all-gather with explicit remote DMA (≅ a hand-written
    ``MPI_Allgather`` over the ring, the device-pointer gather of
    ``mpi_daxpy_nvtx.cc:282-291`` done as w−1 neighbor hops instead of one
    library call). Step ``s`` forwards the out-region received at step
    ``s−1`` (step 0: the own block) straight out of ``out_ref`` to the
    right neighbor's identical region — every region is written by exactly
    ONE incoming DMA, so there is no buffer-slot WAR hazard and no
    backpressure handshake is needed.

    Each step uses its OWN send/recv semaphore pair (``send_sem[s]`` /
    ``recv_sem[s]`` — the DMA analog of the reference's per-direction MPI
    tag separation, ``mpi_stencil_gt.cc:96-106``). A single counting pair
    is NOT enough: nothing bounds how far the left neighbor runs ahead
    (its progress is gated by ITS left, not by us), so two of its DMAs
    can be in flight at once and an anonymous ``recv_sem`` wait could be
    satisfied by the step-``s+1`` arrival — forwarding the step-``s``
    region while it is still being written. This RAW forwarding hazard is
    not an analysis artifact: the round-4 simulated multi-device
    interpreter caught it as a real detected race in the single-pair
    formulation (``tests/test_ring_sync.py``); per-step semaphores make
    the step-``s`` read wait on exactly the step-``s`` write."""
    if loopback_w is not None:
        n_dev = loopback_w
        my = jnp.int32(0)
        right = left = jax.lax.axis_index(axis_name)  # myself
    else:
        n_dev = axis_size(axis_name)
        my = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(my + 1, jnp.int32(n_dev))
        left = jax.lax.rem(my - 1 + jnp.int32(n_dev), jnp.int32(n_dev))
    n = x_ref.shape[0]

    if use_barrier:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    if loopback_w is not None:
        # seed EVERY region with the shard so the self-forwarding loop
        # below moves defined data (final out == tile(x, w)); real
        # hardware then executes every per-step semaphore index and
        # sliced self-DMA of the w-step schedule. NOTE: because each
        # loopback DMA is region -> same region on this device, the
        # value result is identity BY CONSTRUCTION - the mode is a
        # Mosaic compile/execute smoke (alignment errors, bad semaphore
        # shapes, hangs; the class the round-2 hardware audit caught),
        # not a data-path check. Data-path coverage at w>1 lives in the
        # simulated multi-device tests (tests/test_ring_sync.py).
        for i in range(n_dev):
            seed = pltpu.make_async_copy(
                x_ref, out_ref.at[pl.ds(i * n, n)], copy_sem
            )
            seed.start()
            seed.wait()
    else:
        own = pltpu.make_async_copy(
            x_ref, out_ref.at[pl.ds(my * n, n)], copy_sem
        )
        own.start()
        own.wait()

    for step in range(n_dev - 1):
        # region forwarded this step: own block at step 0, then whatever
        # landed last step
        src = jax.lax.rem(
            my - jnp.int32(step) + jnp.int32(n_dev * n_dev),
            jnp.int32(n_dev),
        )
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[pl.ds(src * n, n)],
            dst_ref=out_ref.at[pl.ds(src * n, n)],
            send_sem=send_sem.at[step],
            recv_sem=recv_sem.at[step],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()


def ring_allgather_pallas(
    x,
    *,
    axis_name: str,
    collective_id: int = 9,
    interpret: bool | None = None,
    self_ring: int | None = None,
):
    """Per-shard ring all-gather along axis 0 with explicit inter-chip RDMA
    — the hand-tuned twin of ``lax.all_gather(tiled=True)`` for the
    COLLECTIVE pillar, completing the dual-tier pattern the halo layer has
    (``ring_halo_pallas`` vs ``ppermute``). Call *inside* ``shard_map``.

    ``x`` is this shard's (n, m) block; returns the (w·n, m) gathered array.
    Everything stays HBM-resident (shard-size independent); the alignment
    requirement is that the dynamic row offsets of the out-region DMAs stay
    sublane-tile-aligned: 2-D shards need n rows ≡ 0 mod the dtype's
    sublane tile (8 f32/f64, 16 bf16, 32 int8); 1-D shards are folded into
    128-lane rows (Mosaic sliced DMA needs full lane tiles — a (n, 1) view
    compiles nowhere but interpret mode), so they need
    n ≡ 0 mod 128·sublane (1024 f32, 2048 bf16).

    ``self_ring=k`` (single-device validation mode, the reduce-scatter's
    twin): run the full ``k``-step forwarding schedule with both neighbors
    mapped to this device, every region pre-seeded with the shard; the
    result is ``tile(x, k)``. Unlike the reduce-scatter's loopback (whose
    sum is data-dependent), each self-DMA here is region → same region,
    so the value result is identity by construction — the mode is a
    Mosaic COMPILE/EXECUTE smoke for the per-step semaphore pairs and
    sliced DMAs on real hardware (compile failures, alignment errors,
    hangs), not a data-path check; that lives in
    ``tests/test_ring_sync.py``'s simulated multi-device runs.
    """
    sublane = max(8, 8 * 4 // jnp.dtype(x.dtype).itemsize)
    if x.ndim == 1:
        unit = 128 * sublane
        if x.shape[0] % unit != 0:
            raise ValueError(
                f"ring_allgather_pallas: 1-D shards need n % {unit} == 0 "
                f"for {jnp.dtype(x.dtype).name} (128 lanes × {sublane} "
                f"sublanes per DMA tile), got {x.shape[0]}"
            )
        return ring_allgather_pallas(
            x.reshape(-1, 128),
            axis_name=axis_name,
            collective_id=collective_id,
            interpret=interpret,
            self_ring=self_ring,
        ).reshape(-1)
    n = x.shape[0]
    if n % sublane != 0:
        raise ValueError(
            f"ring_allgather_pallas needs shard rows % {sublane} == 0 for "
            f"{jnp.dtype(x.dtype).name} (sublane tile), got {n}"
        )
    interp = _auto_interpret(interpret)
    n_dev = axis_size(axis_name)
    if self_ring is not None:
        if n_dev != 1 or self_ring < 2:
            raise ValueError(
                f"self_ring={self_ring} is a single-device validation mode "
                f"(needs axis size 1 and self_ring >= 2, got w={n_dev})"
            )
        n_dev = self_ring
    out_struct = jax.ShapeDtypeStruct((n_dev * n, *x.shape[1:]), x.dtype)
    return pl.pallas_call(
        functools.partial(
            _ring_allgather_kernel,
            axis_name=axis_name,
            use_barrier=not _serial_interpret(interp),
            loopback_w=self_ring,
        ),
        out_shape=out_struct,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            # per-step send/recv pairs (≅ per-step MPI tags): see the
            # kernel docstring for the RAW forwarding hazard a single
            # counting pair reintroduces
            pltpu.SemaphoreType.DMA((max(1, n_dev - 1),)),
            pltpu.SemaphoreType.DMA((max(1, n_dev - 1),)),
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(x)


def _ring_reduce_scatter_kernel(x_ref, out_ref, comm_ref, send_ref,
                                acc_a, acc_b, copy_sem, copy_sem2,
                                send_sem, recv_sem, ready_sem,
                                *, axis_name, w, tile_rows, use_barrier,
                                use_handshake, loopback, credits):
    """Ring reduce-scatter with explicit remote DMA: w−1 hops, each
    forwarding a running partial sum one chunk to the right; rank ``r``
    ends owning chunk ``r`` fully reduced (``lax.psum_scatter`` ordering,
    so :func:`_ring_allgather_kernel` composes into a full allreduce — the
    hand twin of the in-place device ``MPI_Allreduce(MPI_SUM)`` of
    ``mpi_stencil2d_gt.cc:615-625``). Step ``s`` sends chunk
    ``(r − s − 1) mod w``: the received partial is folded with the local
    chunk tile-by-tile through VMEM (ANY-space refs cannot feed the VPU
    directly) into the next step's send buffer — or, at the last step,
    into the owned output chunk.

    All remote writes land in ``comm_ref``, which holds ``credits``
    slots (1 = the single-slot default, 2 = the double-buffered
    pod-latency variant); a receiver-backpressure handshake
    (``ready_sem``, remote-signaled by the consumer after a slot is
    folded) keeps an incoming DMA from overrunning unconsumed data. The plain bool interpreter serializes devices
    and cannot run it; on hardware and under the simulated multi-device
    interpreter (``pltpu.InterpretParams``: per-device threads, simulated
    remote DMA) the handshake and the entry barrier are enabled and
    EXECUTED — ``tests/test_ring_sync.py`` runs them at non-loopback
    w ∈ {4, 8} with vector-clock race detection on, including the
    negative control (handshake disabled ⇒ the comm-slot WAW/RAW race is
    detected; enabled ⇒ race-free and exact).

    Why double-buffering ``comm_ref`` ALONE (no credits — the round-2
    advisor suggestion) is unsafe: a sender's progress is gated by its
    LEFT neighbor (``rdma.wait`` waits on its own send landing and its
    own recv arriving — landing, not consumption), so nothing bounds how
    far a rank can run ahead of its RIGHT neighbor's folds; with two
    slots, writes ``s`` and ``s+2`` share a slot and a 2-step skew
    clobbers unconsumed data the same way (the credits=2 negative
    control in ``tests/test_ring_sync.py`` executes exactly this race).
    Safety requires receiver credits: sends ``s ≥ credits`` wait one
    credit, consumers signal after folding slot ``s ≤ w−2−credits`` —
    balanced accounting, ``w−1−credits`` signals vs waits per rank.
    ``credits=2`` additionally needs PER-PARITY recv semaphores
    (``recv_sem[s % 2]``): with two arrivals in flight an anonymous
    counting wait could be satisfied by the ``s+1`` arrival while slot
    ``s % 2`` is still being written — the all-gather's round-4 RAW
    hazard class. Both credit levels run race-free and exact under the
    simulated multi-device interpreter at non-loopback w ∈ {4, 8}; the
    2-credit variant's wall-clock BENEFIT (overlapping send ``s+1``
    with the right neighbor's fold of ``s``) needs real multi-chip skew
    — record a pod run in MULTICHIP evidence when hardware is
    available.

    ``loopback`` runs the full ``w``-step schedule with both neighbors
    mapped to this device (the self-ring validation trick): one chip then
    executes every code path — sliced dynamic DMA, remote self-DMA, the
    VMEM accumulate, the semaphore handshake — and the result is the sum
    of the shard's own ``w`` chunks, checkable on host."""
    my = jax.lax.axis_index(axis_name)
    if loopback:
        right = left = my
    else:
        right = jax.lax.rem(my + 1, jnp.int32(w))
        left = jax.lax.rem(my - 1 + jnp.int32(w), jnp.int32(w))
    cn = comm_ref.shape[0] // credits  # comm_ref holds `credits` slots

    if use_barrier:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    if w == 1:
        own = pltpu.make_async_copy(x_ref, out_ref, copy_sem)
        own.start()
        own.wait()
        return

    wrap = jnp.int32(w * w)  # keeps every modulus operand positive

    # step-0 payload: my chunk (my − 1), verbatim
    c0 = jax.lax.rem(my - 1 + wrap, jnp.int32(w))
    seed = pltpu.make_async_copy(
        x_ref.at[pl.ds(c0 * cn, cn)], send_ref, copy_sem
    )
    seed.start()
    seed.wait()

    for s in range(w - 1):
        sl = s % credits  # comm slot (and recv-semaphore parity)
        if use_handshake and s >= credits:
            # right consumed my payload s - credits; a comm slot is free.
            # credits=1: wait before every send after the first (the slot
            # starts free); credits=2: the first TWO sends are free, so
            # send s+1 overlaps right's consumption of s — the pod-scale
            # latency optimization, slot-safe because writes s and s+2
            # (same slot) are still separated by a consume
            pltpu.semaphore_wait(ready_sem, 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=send_ref,
            dst_ref=comm_ref.at[pl.ds(sl * cn, cn)],
            send_sem=send_sem,
            # per-parity recv semaphores: with 2 credits the left
            # neighbor may have arrivals s and s+1 in flight at once,
            # and an ANONYMOUS counting wait could be satisfied by the
            # s+1 arrival while slot s%2 is still being written — the
            # same hazard class the round-4 race detector caught in the
            # all-gather. Parity sems cannot alias: left's s+2 (same
            # parity) needs my consume-credit for s first.
            recv_sem=recv_sem.at[sl],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        # comm slot sl holds the (s+1)-rank partial of chunk
        # (my − s − 2); fold in my contribution
        c = jax.lax.rem(my - jnp.int32(s) - 2 + wrap, jnp.int32(w))
        dst = out_ref if s == w - 2 else send_ref
        for j in range(cn // tile_rows):
            ca = pltpu.make_async_copy(
                comm_ref.at[pl.ds(sl * cn + j * tile_rows, tile_rows)],
                acc_a, copy_sem,
            )
            cb = pltpu.make_async_copy(
                x_ref.at[pl.ds(c * cn + j * tile_rows, tile_rows)],
                acc_b, copy_sem2,
            )
            ca.start()
            cb.start()
            ca.wait()
            cb.wait()
            acc_a[:] = acc_a[:] + acc_b[:]
            cw = pltpu.make_async_copy(
                acc_a, dst.at[pl.ds(j * tile_rows, tile_rows)], copy_sem
            )
            cw.start()
            cw.wait()
        if use_handshake and s <= w - 2 - credits:
            # tell left a slot freed, releasing its send s + credits (the
            # last `credits` consumes release nothing: nobody sends
            # again). Accounting balances: w − 1 − credits signals vs
            # w − 1 − credits waits per rank.
            pltpu.semaphore_signal(ready_sem, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)


def ring_reduce_scatter_pallas(
    x,
    *,
    axis_name: str,
    collective_id: int = 10,
    interpret: bool | None = None,
    tile_rows: int | None = None,
    self_ring: int | None = None,
    unsafe_no_handshake: bool = False,
    credits: int = 1,
):
    """Per-shard ring reduce-scatter along axis 0 with explicit inter-chip
    RDMA; rank ``r`` returns chunk ``r`` of the elementwise sum (shape
    ``(n/w, …)``). Call *inside* ``shard_map``. Alignment mirrors
    :func:`ring_allgather_pallas`, with the extra factor ``w`` from
    chunking: 2-D shards need rows ≡ 0 mod ``w·sublane``; 1-D shards fold
    into 128-lane rows and need ``n ≡ 0 mod w·128·sublane``.

    ``self_ring=k`` (single-device validation mode, ≅ the periodic
    self-ring the halo benchmarks use): run the full ``k``-step schedule
    with all neighbors mapped to this one device, returning the sum of the
    shard's own ``k`` chunks — so real hardware exercises every loop-body
    code path without a multi-chip slice.

    ``unsafe_no_handshake=True`` disables the receiver-backpressure
    handshake. TESTING ONLY: it exists so the race-detection negative
    control (``tests/test_ring_sync.py``) can prove the simulated
    multi-device interpreter actually sees the comm-slot hazard the
    handshake closes; running it on hardware would be a data race.

    ``credits=2`` selects the double-buffered comm variant (two comm
    slots, per-parity recv semaphores, 2-credit receiver backpressure):
    send ``s+1`` overlaps the right neighbor's consumption of ``s`` — a
    pod-scale latency optimization whose wall-clock benefit needs real
    multi-chip skew to show, but whose CORRECTNESS is executed in CI
    under the simulated multi-device interpreter with race detection
    (round 4; the round-3 analysis that a naive double-buffer WITHOUT
    credits would be unsafe still holds — the negative control
    demonstrates the hazard class)."""
    sublane = max(8, 8 * 4 // jnp.dtype(x.dtype).itemsize)
    w = axis_size(axis_name)
    if self_ring is not None:
        if w != 1 or self_ring < 2:
            raise ValueError(
                f"self_ring={self_ring} is a single-device validation mode "
                f"(needs axis size 1 and self_ring >= 2, got w={w})"
            )
        w = self_ring
    if x.ndim == 1:
        unit = w * 128 * sublane
        if x.shape[0] % unit != 0:
            raise ValueError(
                f"ring_reduce_scatter_pallas: 1-D shards need n % {unit} "
                f"== 0 for {jnp.dtype(x.dtype).name} on a {w}-ring (w × "
                f"128 lanes × {sublane} sublanes), got {x.shape[0]}"
            )
        return ring_reduce_scatter_pallas(
            x.reshape(-1, 128),
            axis_name=axis_name,
            collective_id=collective_id,
            interpret=interpret,
            tile_rows=tile_rows,
            self_ring=self_ring,
            unsafe_no_handshake=unsafe_no_handshake,
            credits=credits,
        ).reshape(-1)
    n = x.shape[0]
    if n % (w * sublane) != 0:
        raise ValueError(
            f"ring_reduce_scatter_pallas needs shard rows % {w * sublane} "
            f"== 0 for {jnp.dtype(x.dtype).name} on a {w}-ring "
            f"(w × sublane tile), got {n}"
        )
    if credits not in (1, 2):
        raise ValueError(f"credits={credits} must be 1 or 2")
    interp = _auto_interpret(interpret)
    cn = n // w
    itemsize = jnp.dtype(x.dtype).itemsize
    minor = int(np.prod(x.shape[1:]))
    # VMEM accumulate tile: ≤ ~2 MB per buffer, a sublane-multiple divisor
    # of the chunk rows (so every sliced DMA stays tile-aligned); the
    # explicit override exists so tests can force the multi-tile loop at
    # small shapes
    if tile_rows is None:
        tile_rows = cn
        budget_rows = max(sublane, (2 << 20) // max(minor * itemsize, 1))
        if tile_rows > budget_rows:
            tile_rows = sublane * _fit_divisor(
                cn // sublane, budget_rows // sublane
            )
    elif cn % tile_rows or tile_rows % sublane:
        raise ValueError(
            f"tile_rows={tile_rows} must divide chunk rows {cn} and be a "
            f"multiple of the {sublane}-row sublane tile"
        )
    if 2 * tile_rows * minor * itemsize > _VMEM_BUDGET_BYTES:
        # even one sublane-tile row per buffer can blow VMEM at very wide
        # minor dims; fail with the explicit error the flash kernels use
        # rather than an opaque Mosaic allocation failure
        raise ValueError(
            f"ring_reduce_scatter_pallas: accumulate tiles "
            f"2 × {tile_rows} × {minor} × {itemsize} B exceed the "
            f"{_VMEM_BUDGET_BYTES // 2**20} MB VMEM budget; reshape the "
            f"shard so rows × row-width shrinks (row width ≤ "
            f"{_VMEM_BUDGET_BYTES // (2 * sublane * itemsize)} elements)"
        )
    chunk = jax.ShapeDtypeStruct((cn, *x.shape[1:]), x.dtype)
    comm = jax.ShapeDtypeStruct((credits * cn, *x.shape[1:]), x.dtype)
    out, _, _ = pl.pallas_call(
        functools.partial(
            _ring_reduce_scatter_kernel,
            axis_name=axis_name,
            w=w,
            tile_rows=tile_rows,
            use_barrier=not _serial_interpret(interp),
            use_handshake=(
                not _serial_interpret(interp) and not unsafe_no_handshake
            ),
            loopback=self_ring is not None,
            credits=credits,
        ),
        out_shape=(chunk, comm, chunk),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 3,
        scratch_shapes=[
            pltpu.VMEM((tile_rows, *x.shape[1:]), x.dtype),
            pltpu.VMEM((tile_rows, *x.shape[1:]), x.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((credits,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(x)
    return out


def ring_allreduce_pallas(
    x,
    *,
    axis_name: str,
    collective_id: int = 10,
    interpret: bool | None = None,
    credits: int = 1,
):
    """Hand-tier ring allreduce: reduce-scatter (w−1 hops) + ring
    all-gather (w−1 hops) — the bandwidth-optimal 2(w−1)/w·n schedule and
    the explicit-RDMA twin of ``lax.psum``, completing the hand collective
    trio (halo / allgather / allreduce ≅ the reference's Isend-Irecv /
    ``MPI_Allgather`` / ``MPI_Allreduce`` pillars). Call *inside*
    ``shard_map``; every rank returns the full elementwise sum.

    Phase ordering between the two kernels needs no global barrier: the
    all-gather kernel's entry neighborhood barrier already guarantees both
    neighbors finished their reduce-scatter before any gather DMA lands.
    Alignment follows :func:`ring_reduce_scatter_pallas`."""
    rs = ring_reduce_scatter_pallas(
        x,
        axis_name=axis_name,
        collective_id=collective_id,
        interpret=interpret,
        credits=credits,
    )
    if axis_size(axis_name) == 1:
        return rs
    # the reduce-scatter's n % w·128·sublane floor implies the allgather's
    # n % 128·sublane, so the chunk always re-enters cleanly (1-D included:
    # the allgather does its own lane fold)
    return ring_allgather_pallas(
        rs,
        axis_name=axis_name,
        collective_id=collective_id + 1,
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Halo pack/unpack staging kernels
# ---------------------------------------------------------------------------


def _pack_kernel(z_ref, lo_ref, hi_ref, *, axis, n_bnd):
    n = z_ref.shape[axis]
    if axis == 0:
        lo_ref[:] = z_ref[pl.ds(n_bnd, n_bnd), :]
        hi_ref[:] = z_ref[pl.ds(n - 2 * n_bnd, n_bnd), :]
    else:
        lo_ref[:] = z_ref[:, pl.ds(n_bnd, n_bnd)]
        hi_ref[:] = z_ref[:, pl.ds(n - 2 * n_bnd, n_bnd)]


@functools.partial(jax.jit, static_argnames=("axis", "n_bnd", "interpret"))
def pack_edges_pallas(z, axis: int = 0, n_bnd: int = N_BND,
                      interpret: bool | None = None):
    """Copy the two interior edge slices into contiguous staging buffers
    (≅ ``buf_from_view``, ``mpi_stencil2d_sycl.cc:82-96``)."""
    shape = list(z.shape)
    shape[axis] = n_bnd
    buf = jax.ShapeDtypeStruct(tuple(shape), z.dtype)
    return pl.pallas_call(
        functools.partial(_pack_kernel, axis=axis, n_bnd=n_bnd),
        out_shape=(buf, buf),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_auto_interpret(interpret),
    )(z)


def _unpack_kernel(z_ref, lo_ref, hi_ref, out_ref, *, axis, n_bnd):
    out_ref[:] = z_ref[:]
    n = z_ref.shape[axis]
    if axis == 0:
        out_ref[pl.ds(0, n_bnd), :] = lo_ref[:]
        out_ref[pl.ds(n - n_bnd, n_bnd), :] = hi_ref[:]
    else:
        out_ref[:, pl.ds(0, n_bnd)] = lo_ref[:]
        out_ref[:, pl.ds(n - n_bnd, n_bnd)] = hi_ref[:]


@functools.partial(jax.jit, static_argnames=("axis", "n_bnd", "interpret"))
def unpack_ghosts_pallas(z, lo_ghost, hi_ghost, axis: int = 0,
                         n_bnd: int = N_BND, interpret: bool | None = None):
    """Write received halo buffers into the ghost regions
    (≅ ``buf_to_view``, ``mpi_stencil2d_sycl.cc:102-116``)."""
    return pl.pallas_call(
        functools.partial(_unpack_kernel, axis=axis, n_bnd=n_bnd),
        out_shape=jax.ShapeDtypeStruct(z.shape, z.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_auto_interpret(interpret),
    )(z, lo_ghost, hi_ghost)


# ---------------------------------------------------------------------------
# Flash attention (long-context pillar, SURVEY §5.7)
# ---------------------------------------------------------------------------


def _fit_divisor(n: int, want: int) -> int:
    """Largest tile ≤ ``want`` that divides ``n`` (≥ 1 always exists)."""
    t = min(want, n)
    while n % t:
        t -= 1
    return t


def _shrink_tiles_to_budget(live, L, Lk, q_tile, k_tile):
    """Shared shrink policy for the flash kernels: halve k_tile (floor 256)
    then q_tile (floor 64) until ``live(qt, kt)`` fits the VMEM budget,
    then snap both to divisors of the block lengths. Returns None when even
    minimum tiles don't fit (the caller decides the fallback/failure)."""
    budget = _VMEM_BUDGET_BYTES
    while live(q_tile, k_tile) > budget and k_tile > 256:
        k_tile //= 2
    while live(q_tile, k_tile) > budget and q_tile > 64:
        q_tile //= 2
    if live(q_tile, k_tile) > budget:
        return None
    return _fit_divisor(L, q_tile), _fit_divisor(Lk, k_tile)


def _fit_flash_tiles(L, Lk, d, itemsize, q_tile, k_tile,
                     f32_upcast=False):
    """Tile fit for the resident-K/V flash kernel. Live model (matches the
    Mosaic stack-OOM sizes observed on v5e): the full K/V blocks
    (2·Lk·d·itemsize) + the scores tile in f32 and its dtype-cast copy
    (q_tile·k_tile·(4+itemsize)) + q/acc/m/l tiles. The round-5 causal
    sub-span path allocates NO extra state (its band sub-spans are
    narrower than the dense scores tile), so causal and non-causal fits
    admit identical tiles — a scratch-based design that diverged the two
    fits was reverted for exactly that reason. ``f32_upcast`` (sub-f32
    inputs at precision=HIGHEST) charges the in-kernel f32 operand
    copies the upcast helpers materialize (q + per-tile K and V slices).
    Returns None when K/V residency alone exceeds VMEM — the caller
    takes the streaming kernel."""

    def live(qt, kt):
        return (
            2 * Lk * d * itemsize
            + qt * kt * (4 + itemsize)
            + qt * (d * (itemsize + 4) + 8)
            + ((qt + 2 * kt) * d * 4 if f32_upcast else 0)
        )

    return _shrink_tiles_to_budget(live, L, Lk, q_tile, k_tile)


# Streaming-path skip_tile default: the measured-on-chip value now
# lives in tune/priors.py (STREAM_SKIP_TILE, with the BASELINE round-5
# streaming-decoupling rationale) — schedule constants are pinned only
# in the tuner's prior tables (rule TPM701). The kernel keeps the alias
# its callers and tests know.
from tpu_mpi_tests.tune.priors import (  # noqa: E402
    STREAM_SKIP_TILE as _STREAM_SKIP_TILE_DEFAULT,
)


def _fit_stream_tiles(L, Lk, d, itemsize, q_tile, k_tile,
                      f32_upcast=False):
    """Tile fit for the streaming-K/V kernel: K/V tiles are grid-blocked
    (double-buffered by the pipeline), so only tiles — never full blocks —
    are resident and any Lk fits. ``f32_upcast`` charges the
    HIGHEST-precision sub-f32 operand copies like the resident fit.
    Unsatisfiable only for huge d, which no tiling can fix — raise the
    constraint instead of the opaque Mosaic scoped-vmem OOM."""

    def live(qt, kt):
        return (
            4 * kt * d * itemsize           # k+v tiles, double-buffered
            + qt * kt * (4 + itemsize)      # scores f32 + dtype-cast copy
            + qt * (d * (itemsize + 4) + 8)
            + ((qt + 2 * kt) * d * 4 if f32_upcast else 0)
        )

    fit = _shrink_tiles_to_budget(live, L, Lk, q_tile, k_tile)
    if fit is None:
        raise ValueError(
            f"flash attention head dim too large for VMEM: d={d} needs "
            f"{live(64, 256) / 2**20:.1f} MiB at minimum tiles vs the "
            f"~{_VMEM_BUDGET_BYTES / 2**20:.0f} MiB budget; split the head "
            f"dimension"
        )
    return fit


def _wants_true_f32(precision) -> bool:
    hp = jax.lax.Precision.HIGHEST
    return precision == hp or precision == (hp, hp)


def _qk_operands(q, kb, precision):
    """HIGHEST-precision matmuls on sub-f32 operands upcast to f32 INSIDE
    the kernel: Mosaic's ``tpu.matmul`` rejects bf16 operands with fp32
    contract precision ("Bad lhs type", hardware-discovered round 5), and
    HIGHEST semantically requests full-f32 arithmetic anyway. f32 inputs
    (and any non-HIGHEST precision) pass through untouched."""
    if _wants_true_f32(precision):
        # each operand independently (callers may pre-hoist the
        # loop-invariant q upcast; a mixed f32×bf16 dot is not legal)
        if q.dtype != jnp.float32:
            q = q.astype(jnp.float32)
        if kb.dtype != jnp.float32:
            kb = kb.astype(jnp.float32)
    return q, kb


def _pv_operands(p, vb, precision):
    """PV-matmul twin of :func:`_qk_operands`: ``p`` is already f32, so
    under HIGHEST+sub-f32 only ``vb`` upcasts (avoiding the lossy
    f32→bf16→f32 round trip a generic helper would take)."""
    if _wants_true_f32(precision) and vb.dtype != jnp.float32:
        return p, vb.astype(jnp.float32)
    return p.astype(vb.dtype), vb


def _flash_block_kernel(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, off_ref,
                        m_out, l_out, acc_out, *, scale, causal,
                        k_tile, skip_tile, precision):
    """One q tile against a full K/V block with the online-softmax carry.

    The scores tile (q_tile × k_tile) lives only in VMEM/registers — the
    (L×Lk) matrix is never materialized (the XLA formulation's weakness:
    HBM round-trips per ring step). Both matmuls ride the MXU with f32
    accumulation; the recurrence matches ``comm.ring.online_softmax_update``
    exactly so the flash and XLA tiers cannot diverge numerically beyond
    reassociation.

    Causal masking works in GLOBAL positions ``pos = off + stride·idx``
    (``off_ref = [q_off, k_off, stride]``): contiguous layouts pass
    stride 1; the striped ring layout passes stride = world.

    Round 5 (VERDICT r4 next #1) decouples the SKIP granularity from the
    RESCALE granularity. The causal loop is split in three regimes:

    * columns fully live for EVERY row of this q tile (below the FIRST
      row's horizon) run mask-free single-pass dense bodies — full
      ``k_tile``-wide tiles, then ``chunk_cols``-wide spans inside the
      partial tile — one carry rescale each, no ``where``, wide MXU
      matmuls;
    * the narrow band crossing the diagonal (< chunk_cols + q_tile
      columns) runs masked ``skip_tile``-wide sub-spans, each with its
      own carry update — per-update cost is confined to the band, so a
      ~half-live striped block costs ~its live matmul FLOPs while the
      bulk keeps wide-tile rescale economics (the round-2 finding that
      narrow tiles everywhere are ~2× slower, BASELINE.md tile-tuning
      row);
    * fully-dead columns beyond the LAST row's horizon are never touched
      (round 3).
    """
    from tpu_mpi_tests.comm.ring import online_softmax_update

    q = q_ref[:]                                        # (qt, d)
    if _wants_true_f32(precision) and q.dtype != jnp.float32:
        # hoist the loop-invariant operand upcast: _qk_operands then
        # sees an f32 q and only casts the per-tile K slice (Mosaic does
        # not guarantee loop-invariant code motion out of fori bodies)
        q = q.astype(jnp.float32)
    m, l, acc = m_ref[:], l_ref[:], acc_ref[:]          # (qt,1)(qt,1)(qt,d)
    qt, d = q.shape
    n_kt = k_ref.shape[0] // k_tile
    stride = off_ref[2]
    # program_id only at kernel top level: the interpret-mode lowering
    # substitutes it in the outer jaxpr, not inside fori_loop bodies
    i_q = pl.program_id(0)
    q_pos = (
        off_ref[0] + stride * (
            i_q * qt + jax.lax.broadcasted_iota(jnp.int32, (qt, 1), 0)
        )
    )

    def dense_span(carry, start, width, masked):
        """One single-pass carry update over columns [start, start+width)
        (``width`` static): the full-width body shared by the k_tile tile
        loop and the mask-free chunk loop."""
        m, l, acc = carry
        kb = k_ref[pl.ds(start, width), :]              # (width, d)
        vb = v_ref[pl.ds(start, width), :]
        s = jax.lax.dot_general(
            *_qk_operands(q, kb, precision), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        ) * scale                                       # (qt, width)
        if masked:
            k_pos = (
                off_ref[1] + stride * (
                    start
                    + jax.lax.broadcasted_iota(jnp.int32, (1, width), 1)
                )
            )
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new, l_new, p, corr = online_softmax_update(m, l, s, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            *_pv_operands(p, vb, precision), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        return m_new, l_new, acc_new

    def dense_body(i, carry, masked):
        return dense_span(carry, i * k_tile, k_tile, masked)

    if not causal:
        m, l, acc = jax.lax.fori_loop(
            0, n_kt, functools.partial(dense_body, masked=False), (m, l, acc)
        )
        m_out[:], l_out[:], acc_out[:] = m, l, acc
        return

    if skip_tile == 0:
        # legacy coupled mode (round 3/4 behavior): full-width mask over
        # every live tile — kept as the interleaved same-window A/B
        # partner for the decoupled path (microbench ``causal`` group)
        q_max = off_ref[0] + stride * ((i_q + 1) * qt - 1)
        lim = q_max - off_ref[1]
        n_live = jnp.where(
            lim < 0, 0, jnp.minimum(lim // stride // k_tile + 1, n_kt)
        )
        m, l, acc = jax.lax.fori_loop(
            0, n_live, functools.partial(dense_body, masked=True),
            (m, l, acc),
        )
        m_out[:], l_out[:], acc_out[:] = m, l, acc
        return

    cap = n_kt * k_tile
    # live-column horizons: C_min from the FIRST query row (columns below
    # it are live for every row → mask-free), C_max from the LAST (columns
    # beyond it are dead for every row → skipped). Positions are monotone
    # in the row index (stride ≥ 1), so both horizons are exact.
    q_min = off_ref[0] + stride * (i_q * qt)
    q_max = off_ref[0] + stride * ((i_q + 1) * qt - 1)
    c_min = jnp.clip((q_min - off_ref[1]) // stride + 1, 0, cap)
    c_max = jnp.clip((q_max - off_ref[1]) // stride + 1, 0, cap)
    n_full = c_min // k_tile

    m, l, acc = jax.lax.fori_loop(
        0, n_full, functools.partial(dense_body, masked=False), (m, l, acc)
    )

    # BOUNDARY REGION: columns [n_full·k_tile, c_max) — the partial-tile
    # remainder plus the diagonal band, width < k_tile + qt. The mask-free
    # prefix fully live for every row (end ≤ C_min — up to a whole tile on
    # the contiguous diagonal) runs chunk_cols-wide dense bodies; the
    # remaining ≤ (chunk_cols + qt)/skip_tile sub-spans to C_max run the
    # dense body at skip_tile width WITH the mask. Each sub-span pays its
    # own carry rescale, but only the narrow band does — the round-2
    # "narrow tiles are 2× slower" cost came from rescaling EVERY tile of
    # the block at fine granularity. (Design history: a scores-scratch
    # two-pass variant with ONE rescale per boundary chunk measured
    # SLOWER than even the coupled path on the self-causal diagonal —
    # the scratch round-trip + separate exp pass cost more than the
    # rescales it saved — and its full-k_tile scratch silently halved
    # the f32 L=8192 fit in the decoupled arm only. Sub-span alignment:
    # skip | chunk | k_tile | Lk, so no sub-span crosses the K block and
    # no dynamic-slice clamp can shift data against the mask positions.)
    base = n_full * k_tile
    chunk_cols = skip_tile * max(1, 1024 // skip_tile)
    chunk_cols = skip_tile * _fit_divisor(
        k_tile // skip_tile, chunk_cols // skip_tile
    )
    n_fc = jnp.maximum(0, (c_min - base) // chunk_cols)  # fully-live chunks

    def dense_chunk_body(c, carry):
        return dense_span(carry, base + c * chunk_cols, chunk_cols, False)

    m, l, acc = jax.lax.fori_loop(0, n_fc, dense_chunk_body, (m, l, acc))

    def band_body(s, carry):
        return dense_span(carry, s * skip_tile, skip_tile, True)

    s0 = (base + n_fc * chunk_cols) // skip_tile
    s1 = (c_max + skip_tile - 1) // skip_tile
    m, l, acc = jax.lax.fori_loop(s0, s1, band_body, (m, l, acc))
    m_out[:], l_out[:], acc_out[:] = m, l, acc


def _flash_stream_kernel(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, off_ref,
                         m_out, l_out, acc_out, *, scale, causal,
                         k_tile, skip_tile, precision):
    """Streaming-K/V flash step: 2-D grid (q tiles × k tiles), K/V tiles
    DMA'd per inner step instead of resident — unbounded sequence length on
    one chip, at the cost of re-streaming K/V once per q tile. The
    accumulators live in the output blocks, which pallas keeps VMEM-resident
    across the inner (same-index) grid dimension: initialized from the
    aliased carry at j=0, folded per k tile, flushed after the last.

    Causal grid cells whose whole k tile lies in the future are SKIPPED
    via ``pl.when`` (both matmuls and the carry update) — positions are
    ``off + stride·idx`` like the resident-K/V kernel. The self-causal
    caller additionally remaps dead cells' K/V index_map onto the last
    live tile so Mosaic elides their DMAs too (same-index revisits are
    not refetched).

    Round 5 (``skip_tile`` > 0): the resident kernel's three-regime split
    applied per CELL — cells fully live for EVERY q row run the mask-free
    full-width body, and the ≤1 boundary cell crossing the diagonal runs
    masked ``skip_tile``-wide sub-spans bounded to the live prefix (each
    with its own carry fold, the band form the resident kernel measured
    best). ``skip_tile=0`` keeps the coupled full-width-mask body for
    every live cell."""
    from tpu_mpi_tests.comm.ring import online_softmax_update

    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_out[:] = m_ref[:]
        l_out[:] = l_ref[:]
        acc_out[:] = acc_ref[:]

    qt = q_ref.shape[0]
    stride = off_ref[2]
    if causal:
        q_min = off_ref[0] + stride * (i * qt)
        q_max = off_ref[0] + stride * ((i + 1) * qt - 1)
        k_min = off_ref[1] + stride * (j * k_tile)
        k_max = off_ref[1] + stride * ((j + 1) * k_tile - 1)
        live = k_min <= q_max
        full = k_max <= q_min if skip_tile else live
    else:
        live = True
        full = True

    def fold_span(s, vb):
        """One carry fold of scores ``s`` against value rows ``vb`` into
        the VMEM-resident output accumulators."""
        m_new, l_new, p, corr = online_softmax_update(
            m_out[:], l_out[:], s, keepdims=True
        )
        acc_out[:] = acc_out[:] * corr + jax.lax.dot_general(
            *_pv_operands(p, vb, precision), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        m_out[:] = m_new
        l_out[:] = l_new

    @pl.when(full)
    def _():
        q = q_ref[:]                                    # (qt, d)
        kb = k_ref[:]                                   # (kt, d)
        s = jax.lax.dot_general(
            *_qk_operands(q, kb, precision), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        ) * scale
        if causal and not skip_tile:
            q_pos = (
                off_ref[0] + stride * (
                    i * qt
                    + jax.lax.broadcasted_iota(jnp.int32, (qt, 1), 0)
                )
            )
            k_pos = (
                off_ref[1] + stride * (
                    j * k_tile
                    + jax.lax.broadcasted_iota(jnp.int32, (1, k_tile), 1)
                )
            )
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        fold_span(s, v_ref[:])

    if causal and skip_tile:
        # boundary cell: masked sub-spans over the live prefix only
        @pl.when(live & jnp.logical_not(full))
        def _():
            q = q_ref[:]
            if _wants_true_f32(precision) and q.dtype != jnp.float32:
                q = q.astype(jnp.float32)  # hoisted out of the sub loop
            q_pos = (
                off_ref[0] + stride * (
                    i * qt
                    + jax.lax.broadcasted_iota(jnp.int32, (qt, 1), 0)
                )
            )
            live_cols = jnp.clip(
                (q_max - k_min) // stride + 1, 0, k_tile
            )
            n_sub = (live_cols + skip_tile - 1) // skip_tile

            def sub(js, _):
                kb = k_ref[pl.ds(js * skip_tile, skip_tile), :]
                s = jax.lax.dot_general(
                    *_qk_operands(q, kb, precision),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=precision,
                ) * scale
                k_pos = (
                    off_ref[1] + stride * (
                        j * k_tile + js * skip_tile
                        + jax.lax.broadcasted_iota(
                            jnp.int32, (1, skip_tile), 1
                        )
                    )
                )
                s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
                fold_span(s, v_ref[pl.ds(js * skip_tile, skip_tile), :])
                return 0

            jax.lax.fori_loop(0, n_sub, sub, 0)


def flash_attention_block_pallas(q, k, v, m, l, acc, q_off, k_off, *,
                                 self_causal: bool = False, **kw):
    """Validating wrapper over :func:`_flash_attention_block_jit` (the
    public name; see its docstring). ``self_causal`` demands LITERAL equal
    offsets — the streaming path's K/V index remap is computed in 0-based
    positions at trace time and silently disagrees with shifted offsets,
    so the requirement is enforced here, outside the jit boundary where
    the offsets are still Python values."""
    if self_causal and not (
        isinstance(q_off, int) and isinstance(k_off, int)
        and q_off == k_off
    ):
        raise ValueError(
            "self_causal=True requires literal (Python int) equal "
            f"q_off/k_off, got {q_off!r}/{k_off!r}"
        )
    return _flash_attention_block_jit(
        q, k, v, m, l, acc, q_off, k_off, self_causal=self_causal, **kw
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "causal", "q_tile", "k_tile", "skip_tile", "interpret",
        "precision", "self_causal",
    ),
    donate_argnums=(3, 4, 5),
)
def _flash_attention_block_jit(
    q, k, v, m, l, acc, q_off, k_off, *,
    scale: float, causal: bool = False,
    q_tile: int = 256, k_tile: int | None = None,
    skip_tile: int | None = None,
    interpret: bool | None = None,
    precision=jax.lax.Precision.HIGHEST,
    pos_stride=1,
    self_causal: bool = False,
):
    """Flash-attention step: fold one K/V block into the online-softmax
    carry ``(m, l, acc)`` (shapes (L,1), (L,1), (L,d), float32; donated and
    aliased in place). ``q_off``/``k_off`` are the global sequence positions
    of ``q[0]``/``k[0]`` (traced scalars — causal masking works across ring
    steps, where the K block's origin rotates). The ring-attention inner
    step (``comm.ring.ring_attention(flash=True)``); calling it once with
    offsets 0 is plain single-block flash attention. ``precision`` defaults
    to HIGHEST like the XLA tier (f32 MXU passes; TPU matmul default
    truncates f32 to bf16 lanes, ~7e-3 abs error at L=1024 d=128) — pass
    ``jax.lax.Precision.DEFAULT`` to trade accuracy for MXU throughput.

    Causal masking runs in global positions ``off + pos_stride·idx``
    (``pos_stride`` is a traced scalar): the striped ring layout passes
    stride = world so each rank's rows interleave globally. Fully-masked
    k tiles are skipped, not masked (round-3; VERDICT r2 weak #1). Round 5
    (VERDICT r4 #1): fully-live columns run mask-free wide dense bodies,
    and only the narrow diagonal band runs masked ``skip_tile``-wide
    sub-spans — each band sub-span pays its OWN carry update, so smaller
    ``skip_tile`` trades finer masking against more rescales within the
    band (the measured break-even is layout-dependent:
    ``comm.ring.MEASURED_BEST_SKIP_TILE``). ``skip_tile=0`` is the
    coupled path (full-width masking over every live tile).
    ``self_causal=True`` (static) requires literal ``q_off == k_off``
    (enforced by the :func:`flash_attention_block_pallas` wrapper) —
    single-block causal self-attention — letting the streaming path also
    elide dead tiles' K/V DMAs via index remapping."""
    if k_tile is None:
        # measured-best defaults (VERDICT r4 #2); the layout-aware table
        # lives with the ring layouts, imported lazily like
        # online_softmax_update (no import cycle). The kernel has no
        # layout notion (pos_stride is traced), so this fallback is the
        # CONTIG entry; ring_attention resolves stripe-aware BEFORE
        # calling here. skip_tile=None resolves PER PATH below — the
        # resident and streaming kernels measured different optima.
        from tpu_mpi_tests.comm.ring import _resolve_k_tile

        k_tile = _resolve_k_tile(None, False)
    L, d = q.shape
    Lk = k.shape[0]
    # shrink requested tiles to (a) the VMEM live-set budget and (b) the
    # largest divisor of the block length, so any shard length and any
    # requested tiling works (the XLA tier accepts arbitrary L; the tiers
    # must stay interchangeable) — oversized/odd requests degrade tile
    # width, they don't fail. When even minimum tiles cannot hold the full
    # K/V blocks resident, fall back to the streaming-K/V kernel (K/V
    # tiles grid-blocked per inner step): slower per call (~re-streams K/V
    # once per q tile) but unbounded in Lk.
    itemsize = jnp.dtype(q.dtype).itemsize
    upcast = _wants_true_f32(precision) and itemsize < 4
    fit = _fit_flash_tiles(L, Lk, d, itemsize, q_tile, k_tile, upcast)
    off = jnp.stack(
        [
            jnp.asarray(q_off, jnp.int32),
            jnp.asarray(k_off, jnp.int32),
            jnp.asarray(pos_stride, jnp.int32),
        ]
    )
    carry = jax.ShapeDtypeStruct((L, 1), jnp.float32)
    operands = (
        q, k, v, m.astype(jnp.float32), l.astype(jnp.float32),
        acc.astype(jnp.float32), off,
    )
    out_shape = (carry, carry, jax.ShapeDtypeStruct((L, d), jnp.float32))

    if fit is not None:
        q_tile, k_tile = fit
        if skip_tile is None:
            from tpu_mpi_tests.comm.ring import _resolve_skip_tile

            skip_tile = _resolve_skip_tile(None, False)
        # skip granularity: largest divisor of k_tile ≤ the requested
        # sub-span width (decoupled from the bulk dense-tile width =
        # k_tile); skip_tile=0 selects the legacy coupled path
        # (full-width masking over every live tile)
        if skip_tile:
            skip_tile = _fit_divisor(k_tile, min(skip_tile, k_tile))
        qspec = pl.BlockSpec((q_tile, d), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
        kvspec = pl.BlockSpec((Lk, d), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
        mlspec = pl.BlockSpec((q_tile, 1), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
        return pl.pallas_call(
            functools.partial(
                _flash_block_kernel, scale=scale, causal=causal,
                k_tile=k_tile, skip_tile=skip_tile, precision=precision,
            ),
            out_shape=out_shape,
            grid=(L // q_tile,),
            in_specs=[qspec, kvspec, kvspec, mlspec, mlspec, qspec,
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=(mlspec, mlspec, qspec),
            input_output_aliases={3: 0, 4: 1, 5: 2},
            interpret=_auto_interpret(interpret),
        )(*operands)

    q_tile, k_tile = _fit_stream_tiles(
        L, Lk, d, itemsize, q_tile, k_tile, upcast
    )
    if skip_tile is None:
        skip_tile = _STREAM_SKIP_TILE_DEFAULT
    # same snap policy as the resident path: band sub-spans must tile the
    # stream k tile exactly (skip | k_tile keeps every slice in-bounds)
    if skip_tile:
        skip_tile = _fit_divisor(k_tile, min(skip_tile, k_tile))
    qspec = pl.BlockSpec((q_tile, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM)
    if causal and self_causal:
        # dead cells (whole k tile in the future) revisit the last LIVE
        # tile's index — Mosaic elides same-index refetches, so the
        # skipped cells cost neither matmuls (pl.when in the kernel) nor
        # K/V DMA traffic; positions are 0-based with a common stride,
        # which cancels out of the tile-level comparison
        qt_, kt_ = q_tile, k_tile

        def kv_index(i, j):
            return (jnp.minimum(j, ((i + 1) * qt_ - 1) // kt_), 0)

        kvspec = pl.BlockSpec((k_tile, d), kv_index,
                              memory_space=pltpu.VMEM)
    else:
        kvspec = pl.BlockSpec((k_tile, d), lambda i, j: (j, 0),
                              memory_space=pltpu.VMEM)
    mlspec = pl.BlockSpec((q_tile, 1), lambda i, j: (i, 0),
                          memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(
            _flash_stream_kernel, scale=scale, causal=causal,
            k_tile=k_tile, skip_tile=skip_tile, precision=precision,
        ),
        out_shape=out_shape,
        grid=(L // q_tile, Lk // k_tile),
        in_specs=[qspec, kvspec, kvspec, mlspec, mlspec, qspec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=(mlspec, mlspec, qspec),
        input_output_aliases={3: 0, 4: 1, 5: 2},
        interpret=_auto_interpret(interpret),
    )(*operands)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "causal", "q_tile", "k_tile", "skip_tile", "interpret",
        "precision",
    ),
)
def flash_attention_pallas(
    q, k, v, *, scale: float | None = None, causal: bool = False,
    q_tile: int = 256, k_tile: int | None = None,
    skip_tile: int | None = None,
    interpret: bool | None = None,
    precision=jax.lax.Precision.HIGHEST,
):
    """Single-device flash attention: softmax(q·kᵀ·scale)·v without ever
    materializing the L×L score matrix (O(L·d) memory). The local-compute
    building block of both sequence-parallel flavors (ring: rotate K/V and
    fold this per block; Ulysses: per-head local attention after the
    all-to-all reshard)."""
    L, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    m = jnp.full((L, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((L, 1), jnp.float32)
    acc = jnp.zeros((L, d), jnp.float32)
    m, l, acc = flash_attention_block_pallas(
        q, k, v, m, l, acc, 0, 0, scale=float(scale), causal=causal,
        q_tile=q_tile, k_tile=k_tile, skip_tile=skip_tile,
        interpret=interpret, precision=precision, self_causal=causal,
    )
    return (acc / l).astype(q.dtype)
