"""Hand-written Pallas TPU kernels: the "raw CUDA/SYCL" tier.

The reference carries every kernel twice: a portable expression-template
version (gtensor, ``mpi_stencil2d_gt.cc``) and a hand-written one (SYCL
``parallel_for``, ``mpi_stencil2d_sycl.cc:53-116``; cuBLAS call,
``daxpy.cu:72-73``). This module is the hand-written tier for TPU — explicit
VMEM staging, DMA pipelines, and tile-aligned grids — mirroring:

* ``daxpy_pallas``       ≅ ``cublasDaxpy`` (``daxpy.cu:72-73``)
* ``stencil2d_pallas``   ≅ ``stencil2d_1d_5`` SYCL kernel
  (``mpi_stencil2d_sycl.cc:53-75``): grid of full-extent strips along the
  non-derivative dim, each strip staged in VMEM where the 5 shifted reads
  are VPU shifts. This is the explicit form of what XLA fuses automatically
  (kernels/stencil.py) — the A/B pair the reference keeps on purpose.
* ``pack_edges_pallas`` / ``unpack_ghosts_pallas`` ≅ ``buf_from_view`` /
  ``buf_to_view`` staging kernels (``mpi_stencil2d_sycl.cc:82-116``).

All kernels run compiled on TPU and in interpreter mode elsewhere
(``interpret=None`` auto-selects), so the same tests cover both.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_mpi_tests.kernels.stencil import N_BND, STENCIL5


def _auto_interpret(interpret: bool | None) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# DAXPY
# ---------------------------------------------------------------------------


def _daxpy_kernel(a_ref, x_ref, y_ref, out_ref):
    out_ref[:] = a_ref[0] * x_ref[:] + y_ref[:]


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def daxpy_pallas(a, x, y, block_rows: int = 512, interpret: bool | None = None):
    """y ← a·x + y on 1-D arrays (≅ ``cublasDaxpy``).

    The array is viewed as (rows, 128) lanes and processed in
    ``block_rows``-row VMEM tiles; n must be a multiple of 128 (driver sizes
    are powers of two, like the reference's 48Mi-per-node sizing).
    """
    n = x.shape[0]
    if n % 128 != 0:
        raise ValueError(f"daxpy_pallas needs n % 128 == 0, got {n}")
    rows = n // 128
    block_rows = min(block_rows, rows)
    x2 = x.reshape(rows, 128)
    y2 = y.reshape(rows, 128)
    a_arr = jnp.asarray(a, x.dtype).reshape(1)
    grid = (pl.cdiv(rows, block_rows),)
    out = pl.pallas_call(
        _daxpy_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 128), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_rows, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=_auto_interpret(interpret),
    )(a_arr, x2, y2)
    return out.reshape(n)


# ---------------------------------------------------------------------------
# 2-D array, 1-D 5-point stencil with explicit halo DMA
# ---------------------------------------------------------------------------


def _stencil_strip_kernel(z_ref, scale_ref, out_ref, *, axis, m):
    # full ghosted extent along `axis` is resident in VMEM; the 5 shifted
    # reads become VPU shifts, accumulated in registers (≅ the SYCL kernel's
    # 5 global loads per output element, but staged once)
    z = z_ref[:]
    acc = None
    # .tolist() → weak python floats: no x64 promotion of f32 blocks
    for k, c in enumerate(STENCIL5.tolist()):
        if c == 0.0:
            continue
        term = c * jax.lax.slice_in_dim(z, k, k + m, axis=axis)
        acc = term if acc is None else acc + term
    out_ref[:] = acc * scale_ref[0]


# VMEM is ~16 MiB/core; input strip + output strip, each double-buffered by
# the pallas pipeline, must fit
_VMEM_BUDGET_BYTES = 14 * 1024 * 1024


def _fit_strip(tile: int, extent: int, rows_bytes: int, min_strip: int) -> int:
    """Largest strip ≤ tile fitting the VMEM budget (``rows_bytes`` = bytes
    per unit strip: 2·(ghosted+interior)·itemsize). Ragged final blocks are
    fine — pallas masks out-of-bounds loads/stores."""
    strip = min(tile, extent)
    while strip > min_strip and strip * rows_bytes > _VMEM_BUDGET_BYTES:
        strip //= 2
    if strip * rows_bytes > _VMEM_BUDGET_BYTES:
        raise ValueError(
            f"stencil2d_pallas: even a {strip}-wide strip of extent "
            f"{extent} exceeds the VMEM budget; use the XLA stencil"
        )
    return strip


@functools.partial(jax.jit, static_argnames=("dim", "tile", "interpret"))
def stencil2d_pallas(
    z,
    scale,
    dim: int = 0,
    tile: int = 256,
    interpret: bool | None = None,
):
    """5-point first derivative along ``dim`` of a 2-D array ghosted along
    ``dim`` (out = in − 2·N_BND there) as a hand-tiled Pallas kernel
    (≅ the SYCL ``stencil2d_1d_5``, ``mpi_stencil2d_sycl.cc:53-75``).

    Tiling: the grid walks the NON-derivative dim in ``tile``-wide strips;
    each strip holds the full ghosted derivative extent in VMEM (Mosaic
    requires HBM slices 8-sublane-aligned, which ghosted interiors never
    are, so the halo travels with the strip). The derivative extent is
    therefore VMEM-bounded (strips auto-shrink to fit the ~14 MiB budget);
    ragged final strips are masked by the pallas pipeline.
    """
    nx, ny = z.shape
    if dim == 0:
        mx, mn = nx - 2 * N_BND, ny  # out shape
        # min_strip 64 lets very tall arrays still fit (lanes pad to 128 in
        # the DMA then — a real bandwidth cost the A/B comparison surfaces)
        strip = _fit_strip(
            tile, mn, 2 * (nx + mx) * z.dtype.itemsize, min_strip=64
        )
        grid = (pl.cdiv(mn, strip),)
        in_spec = pl.BlockSpec(
            (nx, strip), lambda j: (0, j), memory_space=pltpu.VMEM
        )
        out_spec = pl.BlockSpec(
            (mx, strip), lambda j: (0, j), memory_space=pltpu.VMEM
        )
        kernel = functools.partial(_stencil_strip_kernel, axis=0, m=mx)
        out_shape = (mx, mn)
    else:
        mx, mn = nx, ny - 2 * N_BND
        strip = _fit_strip(
            tile, mx, 2 * (ny + mn) * z.dtype.itemsize, min_strip=8
        )
        grid = (pl.cdiv(mx, strip),)
        in_spec = pl.BlockSpec(
            (strip, ny), lambda i: (i, 0), memory_space=pltpu.VMEM
        )
        out_spec = pl.BlockSpec(
            (strip, mn), lambda i: (i, 0), memory_space=pltpu.VMEM
        )
        kernel = functools.partial(_stencil_strip_kernel, axis=1, m=mn)
        out_shape = (mx, mn)

    scale_arr = jnp.asarray(scale, z.dtype).reshape(1)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, z.dtype),
        grid=grid,
        in_specs=[in_spec, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=out_spec,
        interpret=_auto_interpret(interpret),
    )(z, scale_arr)


def _iterate_kernel_dim1(z_ref, scale_eps_ref, out_ref, *, mn):
    z = z_ref[:]
    acc = None
    for k, c in enumerate(STENCIL5.tolist()):
        if c == 0.0:
            continue
        term = c * jax.lax.slice_in_dim(z, k, k + mn, axis=1)
        acc = term if acc is None else acc + term
    interior = (
        jax.lax.slice_in_dim(z, N_BND, N_BND + mn, axis=1)
        + scale_eps_ref[0] * acc
    )
    out_ref[:] = jnp.concatenate(
        [
            jax.lax.slice_in_dim(z, 0, N_BND, axis=1),
            interior,
            jax.lax.slice_in_dim(z, N_BND + mn, 2 * N_BND + mn, axis=1),
        ],
        axis=1,
    )


@functools.partial(jax.jit, static_argnames=("tile", "interpret"),
                   donate_argnums=0)
def stencil2d_iterate_pallas(
    z, scale_eps, tile: int = 64, interpret: bool | None = None
):
    """One in-place Jacobi-style step: ``interior += scale_eps · stencil``
    along dim 1, ghosts preserved — shape-preserving so iterations chain,
    with the input buffer aliased to the output (true in-place; ≅ the
    reference updating ``d_dz`` from ``d_z`` each hot-loop iteration with
    persistent buffers, ``mpi_stencil2d_sycl.cc:218-239``).

    Two HBM passes per call (read z, write z) versus XLA's 6 (one per
    stencil tap + writes) — the VMEM-staged shifts are register-cheap along
    the lane dim. This is the bench.py fast path.
    """
    nx, ny = z.shape
    mn = ny - 2 * N_BND
    strip = _fit_strip(tile, nx, 2 * (ny + ny) * z.dtype.itemsize, min_strip=8)
    se = jnp.asarray(scale_eps, z.dtype).reshape(1)
    return pl.pallas_call(
        functools.partial(_iterate_kernel_dim1, mn=mn),
        out_shape=jax.ShapeDtypeStruct((nx, ny), z.dtype),
        grid=(pl.cdiv(nx, strip),),
        in_specs=[
            pl.BlockSpec((strip, ny), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (strip, ny), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        input_output_aliases={0: 0},
        interpret=_auto_interpret(interpret),
    )(z, se)


# ---------------------------------------------------------------------------
# Ring halo exchange over ICI (inter-chip RDMA)
# ---------------------------------------------------------------------------


def _ring_halo_kernel(z_ref, out_ref, comm, send_sem, recv_sem,
                      *, axis_name, axis, n_bnd, periodic, use_barrier):
    """Bidirectional neighbor exchange with explicit remote DMA
    (≅ the ``MPI_Irecv``/``Isend``/``Waitall`` body of ``boundary_exchange``,
    ``mpi_stencil_gt.cc:96-121``: post both directions, overlap, wait, then
    write ghosts).

    Symmetric form: every device sends both directions on the ring
    (including the wrap-around pair), then non-periodic edge ranks simply
    keep their original physical ghosts — identical masking to the XLA
    ``ppermute`` path, and no conditional semaphore accounting to deadlock.
    comm slot 0 ← left neighbor's hi edge; slot 1 ← right neighbor's lo
    edge.
    """
    n_dev = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    # idx is int32; keep the modulus int32 too (x64 would promote the int)
    right = jax.lax.rem(idx + 1, jnp.int32(n_dev))
    left = jax.lax.rem(idx - 1 + jnp.int32(n_dev), jnp.int32(n_dev))
    size = z_ref.shape[axis]

    if use_barrier:
        # neighborhood barrier: both neighbors have entered this call, so
        # their comm scratch is live and last call's reads are done (guide
        # pattern; protects chained iterations). Hardware only — the
        # interpreter serializes devices, so the hazard cannot occur there,
        # and remote signals are unimplemented in interpret mode.
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    def edge(lo, hi):
        if axis == 0:
            return z_ref.at[pl.ds(lo, hi - lo), :]
        return z_ref.at[:, pl.ds(lo, hi - lo)]

    # my hi edge travels right into their slot 0 ("from_left")
    rdma_hi = pltpu.make_async_remote_copy(
        src_ref=edge(size - 2 * n_bnd, size - n_bnd),
        dst_ref=comm.at[0],
        send_sem=send_sem.at[0],
        recv_sem=recv_sem.at[0],
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    # my lo edge travels left into their slot 1 ("from_right")
    rdma_lo = pltpu.make_async_remote_copy(
        src_ref=edge(n_bnd, 2 * n_bnd),
        dst_ref=comm.at[1],
        send_sem=send_sem.at[1],
        recv_sem=recv_sem.at[1],
        device_id=left,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma_hi.start()
    rdma_lo.start()
    rdma_hi.wait()
    rdma_lo.wait()

    out_ref[:] = z_ref[:]

    @pl.when(jnp.logical_or(bool(periodic), idx > 0))
    def _():
        if axis == 0:
            out_ref[pl.ds(0, n_bnd), :] = comm[0]
        else:
            out_ref[:, pl.ds(0, n_bnd)] = comm[0]

    @pl.when(jnp.logical_or(bool(periodic), idx < n_dev - 1))
    def _():
        if axis == 0:
            out_ref[pl.ds(size - n_bnd, n_bnd), :] = comm[1]
        else:
            out_ref[:, pl.ds(size - n_bnd, n_bnd)] = comm[1]


def ring_halo_pallas(
    z,
    *,
    axis_name: str,
    axis: int = 0,
    n_bnd: int = N_BND,
    periodic: bool = False,
    collective_id: int = 7,
    interpret: bool | None = None,
):
    """Per-shard halo exchange with explicit inter-chip RDMA — the
    hand-tuned analog of ``exchange_shard``'s ``ppermute`` (SURVEY.md §5.8:
    ≅ the manual staged CUDA-aware-MPI path). Call *inside* ``shard_map``
    over ``axis_name``; ghost regions along ``axis`` are filled from ring
    neighbors, physical ghosts kept on non-periodic edges."""
    if z.ndim == 1:
        # 1-D ring (stencil1d): run as an (n, 1) column
        out = ring_halo_pallas(
            z.reshape(-1, 1),
            axis_name=axis_name,
            axis=0,
            n_bnd=n_bnd,
            periodic=periodic,
            collective_id=collective_id,
            interpret=interpret,
        )
        return out.reshape(-1)
    if axis == 0:
        comm_shape = (2, n_bnd, z.shape[1])
    else:
        comm_shape = (2, z.shape[0], n_bnd)
    interp = _auto_interpret(interpret)
    return pl.pallas_call(
        functools.partial(
            _ring_halo_kernel,
            axis_name=axis_name,
            axis=axis,
            n_bnd=n_bnd,
            periodic=periodic,
            use_barrier=not interp,
        ),
        out_shape=jax.ShapeDtypeStruct(z.shape, z.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM(comm_shape, z.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(z)


# ---------------------------------------------------------------------------
# Halo pack/unpack staging kernels
# ---------------------------------------------------------------------------


def _pack_kernel(z_ref, lo_ref, hi_ref, *, axis, n_bnd):
    n = z_ref.shape[axis]
    if axis == 0:
        lo_ref[:] = z_ref[pl.ds(n_bnd, n_bnd), :]
        hi_ref[:] = z_ref[pl.ds(n - 2 * n_bnd, n_bnd), :]
    else:
        lo_ref[:] = z_ref[:, pl.ds(n_bnd, n_bnd)]
        hi_ref[:] = z_ref[:, pl.ds(n - 2 * n_bnd, n_bnd)]


@functools.partial(jax.jit, static_argnames=("axis", "n_bnd", "interpret"))
def pack_edges_pallas(z, axis: int = 0, n_bnd: int = N_BND,
                      interpret: bool | None = None):
    """Copy the two interior edge slices into contiguous staging buffers
    (≅ ``buf_from_view``, ``mpi_stencil2d_sycl.cc:82-96``)."""
    shape = list(z.shape)
    shape[axis] = n_bnd
    buf = jax.ShapeDtypeStruct(tuple(shape), z.dtype)
    return pl.pallas_call(
        functools.partial(_pack_kernel, axis=axis, n_bnd=n_bnd),
        out_shape=(buf, buf),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_auto_interpret(interpret),
    )(z)


def _unpack_kernel(z_ref, lo_ref, hi_ref, out_ref, *, axis, n_bnd):
    out_ref[:] = z_ref[:]
    n = z_ref.shape[axis]
    if axis == 0:
        out_ref[pl.ds(0, n_bnd), :] = lo_ref[:]
        out_ref[pl.ds(n - n_bnd, n_bnd), :] = hi_ref[:]
    else:
        out_ref[:, pl.ds(0, n_bnd)] = lo_ref[:]
        out_ref[:, pl.ds(n - n_bnd, n_bnd)] = hi_ref[:]


@functools.partial(jax.jit, static_argnames=("axis", "n_bnd", "interpret"))
def unpack_ghosts_pallas(z, lo_ghost, hi_ghost, axis: int = 0,
                         n_bnd: int = N_BND, interpret: bool | None = None):
    """Write received halo buffers into the ghost regions
    (≅ ``buf_to_view``, ``mpi_stencil2d_sycl.cc:102-116``)."""
    return pl.pallas_call(
        functools.partial(_unpack_kernel, axis=axis, n_bnd=n_bnd),
        out_shape=jax.ShapeDtypeStruct(z.shape, z.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_auto_interpret(interpret),
    )(z, lo_ghost, hi_ghost)
