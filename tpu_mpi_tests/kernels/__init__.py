"""Compute kernels: the TPU analog of cuBLAS/gtensor/SYCL device code.

Each kernel comes in two flavors, mirroring the reference's own
dual-implementation pattern (gtensor expression templates in
``mpi_stencil2d_gt.cc`` vs hand SYCL kernels in ``mpi_stencil2d_sycl.cc``):

* an XLA-expression version (jnp/lax — the compiler fuses and tiles it), and
* a hand-written Pallas version (``*_pallas``) — the "hand CUDA/SYCL" analog.
"""

# NOTE: kernels.daxpy (the module) is deliberately not shadowed by its
# same-named function here — import the module for daxpy.
from tpu_mpi_tests.kernels.stencil import (  # noqa: F401
    STENCIL5,
    stencil1d_5,
    stencil2d_1d_5,
)
from tpu_mpi_tests.kernels.reductions import (  # noqa: F401
    err_norm,
    sum_axis,
    sum_squares,
)
