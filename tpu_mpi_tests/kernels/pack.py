"""Halo pack/unpack: edge-slice extraction and ghost-region writes.

TPU-native replacement for the reference's staging-buffer machinery:
``buf_from_view``/``buf_to_view`` SYCL kernels (``mpi_stencil2d_sycl.cc:
82-116``), the gtensor view assignments in ``boundary_exchange_x``
(``mpi_stencil2d_gt.cc:166-174,237-251``), and the negative-index slice
helpers (``mpi_stencil2d_sycl_oo.cc:164-266``).

Layout convention (matches arrays/domain.py): a ghosted array has, along the
exchange axis with boundary width ``b``::

    [0:b]        lo ghost      ← filled from left neighbor's hi edge
    [b:2b]       lo edge       → sent to left neighbor
    [n-2b:n-b]   hi edge       → sent to right neighbor
    [n-b:n]      hi ghost      ← filled from right neighbor's lo edge

XLA copies slices when it materializes them, so ``pack_edges`` *is* the
"device staging buffer" of the reference; the Pallas variant
(kernels/pack_pallas.py) makes the copy explicit for the hand-tuned path.
"""

from __future__ import annotations

import jax
from jax import lax


def pack_edges(z, axis: int = 0, n_bnd: int = 2):
    """Extract (lo_edge, hi_edge) interior slices to send to neighbors
    (≅ ``buf_from_view``)."""
    n = z.shape[axis]
    lo = lax.slice_in_dim(z, n_bnd, 2 * n_bnd, axis=axis)
    hi = lax.slice_in_dim(z, n - 2 * n_bnd, n - n_bnd, axis=axis)
    return lo, hi


def unpack_ghosts(z, lo_ghost, hi_ghost, axis: int = 0, n_bnd: int = 2):
    """Write received halo blocks into the ghost regions
    (≅ ``buf_to_view``). Functional: returns the updated array."""
    n = z.shape[axis]
    z = lax.dynamic_update_slice_in_dim(z, lo_ghost, 0, axis=axis)
    z = lax.dynamic_update_slice_in_dim(z, hi_ghost, n - n_bnd, axis=axis)
    return z


def interior(z, axis: int = 0, n_bnd: int = 2):
    """Strip ghosts along ``axis``."""
    return lax.slice_in_dim(z, n_bnd, z.shape[axis] - n_bnd, axis=axis)


pack_edges_jit = jax.jit(pack_edges, static_argnames=("axis", "n_bnd"))
unpack_ghosts_jit = jax.jit(unpack_ghosts, static_argnames=("axis", "n_bnd"))
