"""5-point 4th-order centered first-derivative stencils.

TPU-native replacement for the gtensor expression templates
(``mpi_stencil_gt.cc:54-59``, ``mpi_stencil2d_gt.cc:84-110``) and the SYCL
kernel (``mpi_stencil2d_sycl.cc:53-75``). Coefficients are the standard
4th-order central difference (1/12, -2/3, 0, 2/3, -1/12); the input carries
``n_bnd = 2`` ghost points per side along the stencil axis and the output is
the interior (input size − 4 along that axis).

Written as shifted slices summed into one expression — XLA fuses this into a
single VPU pass over the array, which is the idiomatic TPU form of the
reference's lazy expression templates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# 4th-order central first-derivative coefficients (× 1/delta).
STENCIL5 = np.array([1.0 / 12.0, -2.0 / 3.0, 0.0, 2.0 / 3.0, -1.0 / 12.0])
N_BND = 2  # (len(STENCIL5) - 1) // 2


def stencil1d_5(y, scale=1.0, axis: int = 0):
    """Apply the 5-point stencil along ``axis``.

    ``y`` is ghosted along ``axis``; result has ``y.shape[axis] - 4`` there.
    ``scale`` is 1/delta (the reference multiplies by ``scale`` after the
    stencil, ``mpi_stencil_gt.cc:206``).
    """
    n = y.shape[axis]
    if n < 2 * N_BND + 1:
        raise ValueError(
            f"stencil axis {axis} needs >= {2 * N_BND + 1} points, got {n}"
        )
    out = None
    # .tolist() → weak python floats: no x64 promotion of f32 inputs
    for k, c in enumerate(STENCIL5.tolist()):
        if c == 0.0:
            continue
        term = c * lax.slice_in_dim(y, k, n - 2 * N_BND + k, axis=axis)
        out = term if out is None else out + term
    return out * scale


def stencil2d_1d_5(z, scale=1.0, dim: int = 0):
    """2-D array, 1-D stencil along ``dim`` (≅ ``stencil2d_1d_5_d0/_d1``,
    ``mpi_stencil2d_gt.cc:84-110``)."""
    return stencil1d_5(z, scale=scale, axis=dim)


stencil1d_5_jit = jax.jit(stencil1d_5, static_argnames=("axis",))
stencil2d_1d_5_jit = jax.jit(stencil2d_1d_5, static_argnames=("dim",))


def dual_dim_step(z, n_bnd: int, scale_x: float, scale_y: float):
    """Both-dim derivative + residual of a block ghosted along both axes —
    the flagship per-shard pipeline (≅ ``stencil2d_1d_5_d0`` + ``_d1`` +
    ``gt::sum_squares``, ``mpi_stencil2d_gt.cc:84-110,555``).

    Returns ``(dz_dx, dz_dy, residual)``; the derivatives have the ghost
    frame stripped (interior shape in both dims).
    """
    if n_bnd != N_BND:
        raise ValueError(
            f"dual_dim_step requires n_bnd == {N_BND} (the 5-point stencil "
            f"strips exactly 2*{N_BND} along its axis), got {n_bnd}"
        )
    zx = lax.slice_in_dim(z, n_bnd, z.shape[1] - n_bnd, axis=1)
    dz_dx = stencil1d_5(zx, scale=scale_x, axis=0)
    zy = lax.slice_in_dim(z, n_bnd, z.shape[0] - n_bnd, axis=0)
    dz_dy = stencil1d_5(zy, scale=scale_y, axis=1)
    residual = jnp.sum(jnp.square(dz_dx)) + jnp.sum(jnp.square(dz_dy))
    return dz_dx, dz_dy, residual


def analytic_pairs():
    """The reference's test functions: (f, df) pairs used by the drivers.

    1-D: y = x³, dy/dx = 3x² (``mpi_stencil_gt.cc:171-172``).
    2-D: z = x³ + y², dz/dx = 3x², dz/dy = 2y
    (``mpi_stencil2d_gt.cc:431-433``).
    """

    def x_cubed(x):
        return x**3

    def x_cubed_deriv(x):
        return 3 * x**2

    def z_fn(x, y):
        return x**3 + y**2

    def dz_dx(x, y):
        return 3 * x**2 + 0 * y

    def dz_dy(x, y):
        return 0 * x + 2 * y

    return {
        "1d": (x_cubed, x_cubed_deriv),
        "2d_dim0": (z_fn, dz_dx),
        "2d_dim1": (z_fn, dz_dy),
    }
