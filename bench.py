"""Headline benchmark: 2-D stencil full-step throughput at 8192².

Runs the flagship per-iteration pipeline — halo exchange + 5-point stencil
derivative + in-place interior update, the ``mpi_stencil2d_gt.cc:511-535``
hot loop — on an 8192×8192 domain and prints ONE JSON line.

Fast path (TPU, one device, temporal blocking on): the resident-block
schedule (``halo.iterate_pallas_blocks_fn``) — the domain lives as S=2
separate buffers so each runs the full-height dim-0 (sublane-tap)
in-place Pallas kernel with static boundary flags, and the inter-block
ghost refresh is a narrow in-chip band copy; 2 HBM passes per k-group
versus the XLA formulation's ~6 per step. Multi-device (or
``TPU_MPI_BENCH_BLOCKS=0``) uses the dim-1 single-buffer kernel sharded
over the mesh. Iterations chain in one device-side ``lax.fori_loop``;
two run lengths are differenced to cancel the fixed controller round-trip
(~106 ms on the axon TPU tunnel, whose ``block_until_ready`` does not
actually wait — see ``tpu_mpi_tests/instrument/timers.py``).

Baseline: the reference publishes no numbers (BASELINE.md); the comparison
point is the V100 roofline for the same loop at the SAME element width as
the measurement — (2 reads + 1 write) × itemsize × 8192² bytes/iter over
~810 GB/s STREAM-class HBM2 bandwidth ≈ 1006 iter/s for f32, 2012 for a
16-bit element. ``vs_baseline`` is measured iter/s over that equal-width
point, so the ratio is a hardware/kernel comparison, not a dtype-width
artifact; the reference's native-f64 roofline (503 iter/s) is kept as
secondary context in BASELINE.md.

Round 5 (VERDICT r4 #3): ONE invocation measures BOTH official dtypes.
The primary dtype (``TPU_MPI_BENCH_DTYPE``, default float32) keeps the
top-level headline fields for cross-round comparability; the other dtype
runs its own measured-best schedule in the same process/window and lands
as a same-shaped sub-object under its dtype name — so the driver-captured
``BENCH_r{N}.json`` carries the repo's fastest official number (bf16
dim-1, k≥2 temporal blocking — BASELINE.md round-2/3 bf16 findings)
without env vars. ``TPU_MPI_BENCH_SECOND_DTYPE=none`` disables the
second measurement; an explicit ``TPU_MPI_BENCH_BLOCKS`` override applies
to the PRIMARY dtype only (the secondary always runs its default
schedule, keeping the sub-object's meaning fixed).
"""

from __future__ import annotations

import json
import os
import statistics

V100_HBM_GBPS = 810.0  # STREAM-class HBM2 measured-class bandwidth
V100_F64_ITERS_PER_S = 503.0  # 810e9 / (3 * 8 * 8192**2), reference dtype


def _tune_emit(rec) -> None:
    # stdout stays the one JSON result line; sweep records go to stderr
    import sys

    print(json.dumps(rec), file=sys.stderr, flush=True)


def _topo_suffix(world: int) -> str:
    """Topology token for bench schedule strings (``_h1x8``): hosts x
    ranks-per-host, stamped UNCONDITIONALLY — a flat/declared-flat run
    reads ``h1x<world>`` — so BENCH_r* rounds stay attributable when
    runs move across slice shapes. Ragged shapes stamp hosts only (no
    honest single rph number)."""
    from tpu_mpi_tests.comm.topology import current

    t = current()
    if t.is_flat:
        return f"_h1x{world}"
    rph = t.ranks_per_host
    return f"_h{t.num_hosts}x{rph}" if rph else f"_h{t.num_hosts}"


def _resolve_steps(env_val: "str | None", *, n: int, world: int) -> int:
    """Temporal-blocking depth: explicit env > cached winner > shipped
    prior (tune/priors.BENCH_STEPS) — the bench precedence contract,
    pinned by tests/test_tune.py."""
    if env_val is not None:
        return int(env_val)
    from tpu_mpi_tests.tune import priors, registry

    return int(registry.resolve(
        "stencil/steps", prior=priors.BENCH_STEPS,
        device_fallback=False, n=n, world=world,
    ))


def _resolve_blocks(blocks_env: "str | None", dtype_name: str, *, n: int,
                    world: int) -> int:
    """Resident-block count: explicit TPU_MPI_BENCH_BLOCKS > cached
    winner > the dtype's shipped prior (tune/priors.BENCH_BLOCKS —
    BASELINE round-3/5 measured-best: S=2 at f32, single-buffer dim-1
    at bf16)."""
    if blocks_env is not None:
        return int(blocks_env)
    from tpu_mpi_tests.tune import priors, registry

    prior = priors.BENCH_BLOCKS.get(
        dtype_name, priors.BENCH_BLOCKS["float32"]
    )
    # device_fallback=False: the block count is dtype-keyed (f32 wants
    # S=2, bf16 wants the single-buffer schedule) — the other dtype's
    # winner must not leak in through the device-only slot
    return int(registry.resolve(
        "stencil/blocks", prior=prior, device_fallback=False,
        dtype=dtype_name, n=n, world=world,
    ))


def _resolve_tier(env_val: "str | None", dtype_name: str, *, n: int,
                  world: int, platform: str) -> str:
    """Kernel tier of the per-iteration pipeline (ISSUE 15): explicit
    TPU_MPI_BENCH_TIER > cached winner > shipped prior ("blocks" — the
    pre-ISSUE-15 schedule family, byte-identical untuned). The hand
    tiers need the TPU backend; everywhere else the tier is declined to
    "xla" (with a stderr NOTE when explicitly requested) — the schedule
    string must never claim a tier that did not run."""
    from tpu_mpi_tests.comm.halo import STENCIL_TIERS, resolve_stencil_tier

    if env_val is not None and env_val not in STENCIL_TIERS:
        raise SystemExit(
            f"TPU_MPI_BENCH_TIER={env_val!r} unsupported "
            f"({' | '.join(STENCIL_TIERS)})"
        )
    if platform != "tpu":
        if env_val is not None and env_val != "xla":
            from tpu_mpi_tests.drivers._common import decline_note

            decline_note(
                f"TPU_MPI_BENCH_TIER={env_val} not applicable "
                f"(platform={platform}); running the xla tier"
            )
        return "xla"
    return resolve_stencil_tier(
        env_val, dtype=dtype_name, n=n, world=world
    )


def _resolve_overlap(env_val: "str | None", dtype_name: str, *, n: int,
                     world: int) -> int:
    """Halo pipeline depth for the bench schedule: explicit
    TPU_MPI_BENCH_OVERLAP > cached winner > shipped prior (1 — the
    serialized schedule, byte-identical to the pre-overlap era)."""
    if env_val is not None:
        return max(1, min(int(env_val), 2))
    from tpu_mpi_tests.comm.halo import resolve_overlap_depth

    return resolve_overlap_depth(None, dtype=dtype_name, n=n, world=world)


def _build_schedule(dtype_name: str, *, n, steps, world, mesh, axis_name,
                    topo, n_blocks: int, ov_depth: int = 1,
                    tier: str = "blocks", report_declined: bool = False):
    """Build one per-iteration schedule:
    ``(run, state, use_blocks, ov_eff, bench_dim, tier)``.

    ``tier`` selects the kernel tier of the hot loop (ISSUE 15 —
    resolved via the ``stencil/tier`` schedule space by the caller):

    * ``"blocks"`` — the ppermute hand tier, parameterized by the
      ``stencil/blocks`` knob: the resident-block schedule where it
      applies (TPU, k>1, divisible shard), else the dim-1 single-buffer
      kernel — the pre-ISSUE-15 schedule family, byte-identical.
    * ``"rdma-chained"`` — the hand RDMA ring feeding the in-place
      kernel as two chained launches (``iterate_pallas_fn(rdma=True)``).
    * ``"rdma-fused"`` — the ONE-launch fused halo+stencil kernel
      (in-kernel RDMA overlapped with interior compute,
      ``iterate_fused_rdma_fn``) on the dim-0 streaming decomposition.
    * ``"xla"`` — the XLA formulation (shallow ghosts, per-step
      exchange); also the only tier off-TPU, where interpret-mode
      pallas is far too slow.

    ``ov_depth >= 2`` selects the comm/compute-overlap step
    (``halo.iterate_overlap_fn``) where it applies: TPU, the blocks
    tier's dim-1 single-buffer path, ``steps == 1``. Any declined knob
    prints a stderr NOTE and the returned ``tier``/``ov_eff`` name what
    actually ran — the schedule string must never claim a schedule that
    did not run."""
    import jax.numpy as jnp
    import numpy as np

    from tpu_mpi_tests.arrays.domain import Domain2D
    from tpu_mpi_tests.comm.collectives import shard_blocks
    from tpu_mpi_tests.comm.halo import (
        iterate_fused_fn,
        iterate_fused_rdma_fn,
        iterate_pallas_fn,
    )
    from tpu_mpi_tests.kernels.stencil import N_BND, analytic_pairs

    dtype = np.dtype(jnp.bfloat16) if dtype_name == "bfloat16" \
        else np.dtype(np.float32)
    eps = 1e-6
    if topo.platform != "tpu":
        # the hand tiers need the TPU backend (interpret-mode pallas is
        # orders of magnitude off); the resolver already declines them,
        # this guard keeps direct callers honest too
        tier = "xla"
    use_blocks = (
        tier == "blocks" and topo.platform == "tpu" and steps > 1
        and n_blocks >= 2 and (n // world) % n_blocks == 0
    )
    if report_declined and tier == "blocks" and n_blocks >= 2 \
            and not use_blocks:
        # never silently mis-attribute a schedule: a requested block count
        # that fails the gate is reported (stderr — stdout stays the one
        # JSON line) and the JSON records the schedule that actually ran
        from tpu_mpi_tests.drivers._common import decline_note

        decline_note(
            f"TPU_MPI_BENCH_BLOCKS={n_blocks} not applicable "
            f"(platform={topo.platform} world={world} steps={steps} "
            f"n={n}); running the dim-1 single-buffer schedule"
        )
    bench_dim = 0 if (use_blocks or tier == "rdma-fused") else 1
    d = Domain2D(
        n_local_deriv=n // world,
        n_global_other=n,
        n_shards=world,
        dim=bench_dim,
        n_bnd=N_BND * steps,
    )
    f, _ = analytic_pairs()[f"2d_dim{bench_dim}"]
    zg = shard_blocks(
        mesh,
        d.global_ghosted_shape,
        dtype,
        lambda r: d.init_shard(f, r, dtype),
        axis=bench_dim,
    )
    ov_eff = 1
    if (
        ov_depth >= 2 and topo.platform == "tpu" and steps == 1
        and not use_blocks and tier == "blocks"
    ):
        ov_eff = 2
    elif ov_depth >= 2:
        from tpu_mpi_tests.drivers._common import decline_note

        decline_note(
            f"overlap depth {ov_depth} not applicable "
            f"(platform={topo.platform} steps={steps} "
            f"blocks={n_blocks} tier={tier}); running the serialized "
            f"schedule (_ov1)"
        )
    if use_blocks:
        from tpu_mpi_tests.comm.halo import (
            iterate_pallas_blocks_fn,
            split_blocks,
        )

        bench_mesh = None if world == 1 else mesh
        run = iterate_pallas_blocks_fn(
            n_blocks, d.n_bnd, eps * d.scale, steps=steps,
            mesh=bench_mesh, axis_name=axis_name,
        )
        zg = split_blocks(zg, n_blocks, d.n_bnd, mesh=bench_mesh)
    elif tier == "rdma-fused":
        import jax

        run = iterate_fused_rdma_fn(
            mesh, axis_name, d.n_bnd, eps * d.scale, steps=steps
        )
        # the fused kernel's geometry checks (seam blocking, VMEM fit)
        # fire at trace time, not at factory time — probe them NOW so an
        # infeasible geometry raises inside the caller's degrade path
        # instead of crashing the first timed call. The probe traces the
        # compute-only twin (identical geometry path) so the watchdog
        # flight recorder never sees a phantom fused-RDMA dispatch note
        # for a program that never executes.
        jax.eval_shape(
            iterate_fused_rdma_fn(
                mesh, axis_name, d.n_bnd, eps * d.scale, steps=steps,
                local_only=True,
            ),
            zg, 1,
        )
    elif tier == "rdma-chained":
        run = iterate_pallas_fn(
            mesh, axis_name, d.n_bnd, eps * d.scale, steps=steps,
            rdma=True,
        )
    elif ov_eff >= 2:
        from tpu_mpi_tests.comm.halo import iterate_overlap_fn

        run = iterate_overlap_fn(
            mesh, axis_name, d.n_bnd, eps * d.scale, axis=bench_dim
        )
    elif tier == "blocks":  # dim-1 single-buffer hand kernel (blocks=0)
        run = iterate_pallas_fn(
            mesh, axis_name, d.n_bnd, eps * d.scale, steps=steps
        )
    else:  # the XLA tier (and the only CPU path)
        run = iterate_fused_fn(mesh, axis_name, 1, 2, d.n_bnd, d.scale, eps)
    return run, zg, use_blocks, ov_eff, bench_dim, tier


def _measure(dtype_name: str, *, n, steps, world, mesh, axis_name, topo,
             blocks_env: str | None, overlap_env: str | None = None,
             tier_env: str | None = None):
    """One dtype's full measurement: resolve the schedule (explicit env >
    cached winner > prior; TPU_MPI_BENCH_TUNE=1 sweeps kernel-tier and
    block-count candidates on a cache miss first), chain-time it,
    median-of-samples. Returns the JSON-ready dict (top-level field
    shapes; the caller nests the secondary dtype's copy)."""
    import jax.numpy as jnp
    import numpy as np

    from tpu_mpi_tests.instrument.timers import chain_rate
    from tpu_mpi_tests.tune import registry as _tr

    dtype = np.dtype(jnp.bfloat16) if dtype_name == "bfloat16" \
        else np.dtype(np.float32)

    tier = _resolve_tier(tier_env, dtype_name, n=n, world=world,
                         platform=topo.platform)
    tier_miss = topo.platform == "tpu" and _tr.lookup(
        "stencil/tier", device_fallback=False,
        dtype=dtype_name, n=n, world=world,
    ) is None
    if tier_env is None and tier_miss and _tr.tuning_enabled():
        # on-miss kernel-tier sweep (ISSUE 15): price the one-launch
        # fused tier against blocks / chained RDMA / XLA — prior-first,
        # a candidate whose gate declines RAISES so the record can never
        # credit a tier with another tier's seconds
        from tpu_mpi_tests.tune import priors as _priors
        from tpu_mpi_tests.tune.sweep import sweep as _sweep

        sp = _tr.space("stencil/tier")
        cands = [_priors.STENCIL_TIER] + [
            c for c in sp.candidates if c != _priors.STENCIL_TIER
        ]
        n_blocks_t = _resolve_blocks(blocks_env, dtype_name, n=n,
                                     world=world)

        def measure_tier(cand):
            steps_c = 1 if cand == "xla" else steps
            run_c, zg_c, _, _, _, tier_eff = _build_schedule(
                dtype_name, n=n, steps=steps_c, world=world, mesh=mesh,
                axis_name=axis_name, topo=topo, n_blocks=n_blocks_t,
                tier=str(cand),
            )
            if tier_eff != cand:
                raise ValueError(
                    f"tier={cand} not applicable "
                    f"(platform={topo.platform} steps={steps} n={n} "
                    f"world={world})"
                )
            sec, zg_c = chain_rate(run_c, zg_c, n_short=5, n_long=55)
            del zg_c
            # normalize to per-TIMESTEP seconds: the xla candidate
            # advances one timestep per call, the k-step tiers k
            return sec / steps_c

        tier = str(_sweep(
            "stencil/tier", measure_tier, candidates=cands,
            emit=_tune_emit, dtype=dtype_name, n=n, world=world,
        ))
    if tier == "xla":
        steps = 1  # the XLA iterate runs shallow halos, 1 timestep/call

    n_blocks = _resolve_blocks(blocks_env, dtype_name, n=n, world=world)
    cache_miss = _tr.lookup(
        "stencil/blocks", device_fallback=False,
        dtype=dtype_name, n=n, world=world,
    ) is None
    if blocks_env is None and cache_miss and _tr.tuning_enabled() \
            and tier == "blocks":
        # on-miss only (a warmed cache entry IS the swept winner), and
        # prior-first: the budget-exempt first slot must measure THIS
        # dtype's shipped prior, never a value inherited elsewhere
        from tpu_mpi_tests.tune import priors as _priors
        from tpu_mpi_tests.tune.sweep import sweep as _sweep

        sp = _tr.space("stencil/blocks")
        prior = _priors.BENCH_BLOCKS.get(
            dtype_name, _priors.BENCH_BLOCKS["float32"]
        )
        cands = [prior] + [c for c in sp.candidates if c != prior]

        def measure_blocks(cand):
            run_c, zg_c, ub, *_rest = _build_schedule(
                dtype_name, n=n, steps=steps, world=world, mesh=mesh,
                axis_name=axis_name, topo=topo, n_blocks=int(cand),
            )
            if int(cand) >= 2 and not ub:
                raise ValueError(
                    f"blocks={cand} not applicable "
                    f"(platform={topo.platform} steps={steps} n={n} "
                    f"world={world})"
                )
            sec, zg_c = chain_rate(run_c, zg_c, n_short=5, n_long=55)
            del zg_c
            return sec

        n_blocks = int(_sweep(
            "stencil/blocks", measure_blocks, candidates=cands,
            emit=_tune_emit, dtype=dtype_name, n=n, world=world,
        ))

    ov_depth = _resolve_overlap(overlap_env, dtype_name, n=n, world=world)
    try:
        run, zg, use_blocks, ov_eff, bench_dim, tier = _build_schedule(
            dtype_name, n=n, steps=steps, world=world, mesh=mesh,
            axis_name=axis_name, topo=topo, n_blocks=n_blocks,
            ov_depth=ov_depth, tier=tier,
            report_declined=blocks_env is not None,
        )
    except ValueError as e:
        # a cached/requested tier infeasible at THIS geometry (e.g. the
        # fused tier's seam blocking) degrades to the prior tier with a
        # visible NOTE — never a dead headline, never a mislabeled one
        from tpu_mpi_tests.drivers._common import decline_note

        decline_note(
            f"tier {tier} infeasible at n={n} world={world} "
            f"steps={steps} ({e}); running the blocks tier"
        )
        run, zg, use_blocks, ov_eff, bench_dim, tier = _build_schedule(
            dtype_name, n=n, steps=steps, world=world, mesh=mesh,
            axis_name=axis_name, topo=topo, n_blocks=n_blocks,
            ov_depth=ov_depth, tier="blocks",
            report_declined=blocks_env is not None,
        )

    n_short = int(os.environ.get("TPU_MPI_BENCH_ITERS_SHORT", 100))
    # 2100 (2000-iteration delta ≈ 1.7 s device time) keeps the shared
    # tunnel chip's minute-scale contention noise to a few percent; the
    # round-1 1100 default under-measured by ~4%. Counts are in TIMESTEPS;
    # the outer chain length divides by `steps` (each call advances k).
    n_long = int(os.environ.get("TPU_MPI_BENCH_ITERS_LONG", 2100))
    n_short = max(1, n_short // steps)
    n_long = max(n_short + 1, n_long // steps)
    # median of 5 chained measurements: the shared chip's contention
    # windows spread single samples ~±5-8% (BASELINE.md round-2 note);
    # the compiled fn and state are reused, so the extra samples cost
    # only device time (~2 s each)
    n_samples = int(os.environ.get("TPU_MPI_BENCH_SAMPLES", 5))
    samples = []
    for _ in range(max(1, n_samples)):
        sec_per_call, zg = chain_rate(run, zg, n_short=n_short, n_long=n_long)
        samples.append(steps / sec_per_call)
    finite = [s for s in samples if np.isfinite(s)]
    iters_per_s = statistics.median(finite) if finite else float("nan")

    # equal-width V100 roofline for the official 8192² workload: (2 reads
    # + 1 write) × itemsize — 1006 iter/s f32, 2012 at 16-bit
    equal_width_baseline = V100_HBM_GBPS * 1e9 / (3 * dtype.itemsize
                                                  * 8192**2)
    # HBM watermark at the end of this dtype's measurement window —
    # present only where the backend reports allocator stats (absent on
    # CPU/fake devices, never a fake zero). The peak is the process
    # watermark so far (no reset hook on current jaxlibs): the primary
    # dtype's field is its own window; the secondary's includes the
    # primary's footprint — the per-dtype sub-records stay comparable
    # across rounds because the dtype order is fixed.
    hbm = {}
    try:
        from tpu_mpi_tests.instrument.memwatch import device_memory_stats

        stats = device_memory_stats()
        if stats:
            hbm["hbm_peak_bytes"] = max(
                s.get("peak_bytes_in_use", 0) for s in stats.values()
            )
            hbm["hbm_bytes_in_use"] = sum(
                s.get("bytes_in_use", 0) for s in stats.values()
            )
    except Exception:
        hbm = {}
    return {
        **hbm,
        "value": round(iters_per_s, 2),
        "unit": "iter/s",
        "vs_baseline": round(iters_per_s / equal_width_baseline, 3),
        "vs_f64_reference_roofline": round(
            iters_per_s / V100_F64_ITERS_PER_S, 3
        ),
        "dtype": dtype_name,
        # invalid samples become JSON null, not a bare NaN token
        # that would break strict parsers
        "samples": [
            round(s, 2) if np.isfinite(s) else None for s in samples
        ],
        # which per-iteration schedule actually ran (the blocks gate
        # can decline a requested TPU_MPI_BENCH_BLOCKS, the overlap
        # gate a requested depth, the tier gate a requested tier) —
        # the _ov<d> suffix attributes the row to a pipeline depth, the
        # next token to the executing KERNEL TIER (ISSUE 15: blocks /
        # rdma-chained / rdma-fused / xla), and the trailing _h<H>x<R>
        # token to the host topology the run measured on (ISSUE 20) —
        # so BENCH_r* rounds are attributable to a tier AND a slice
        # shape, not just blocks/steps
        "schedule": (
            f"blocks{n_blocks}_dim0_world{world}_{dtype_name}"
            f"_ov{ov_eff}_{tier}{_topo_suffix(world)}"
            if use_blocks
            else f"dim{bench_dim}_world{world}_{dtype_name}"
                 f"_ov{ov_eff}_{tier}{_topo_suffix(world)}"
        ),
        "steps": steps,
        "tier": tier,
        "topology": _topo_suffix(world).lstrip("_"),
    }


def main() -> None:
    from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh, topology
    from tpu_mpi_tests.utils import check_divisible

    # TPU_MPI_BENCH_N / _FAKE_DEVICES shrink the run for CI smoke; the
    # official metric is the 8192 default on real hardware (the baseline
    # constant assumes it)
    n = int(os.environ.get("TPU_MPI_BENCH_N", 8192))
    dtype_name = os.environ.get("TPU_MPI_BENCH_DTYPE", "float32")
    if dtype_name not in ("float32", "bfloat16"):
        raise SystemExit(
            f"TPU_MPI_BENCH_DTYPE={dtype_name!r} unsupported "
            "(float32 | bfloat16)"
        )
    n_fake = int(os.environ.get("TPU_MPI_BENCH_FAKE_DEVICES", "0"))
    if n_fake > 0:  # 0 = off, matching the drivers' --fake-devices default
        from tpu_mpi_tests.drivers._common import force_cpu_devices

        force_cpu_devices(n_fake)
    bootstrap()
    topo = topology()
    world = topo.global_device_count
    mesh = make_mesh()
    axis_name = mesh.axis_names[0]
    check_divisible(n, world, "bench domain over devices")

    # schedule cache: bench consults a warmed cache (default path or
    # TPU_MPI_TUNE_CACHE) so the headline number is the tuned schedule;
    # TPU_MPI_BENCH_TUNE=1 arms the on-miss block-count sweep. With no
    # cache file and no tune flag the registry stays unconfigured and
    # every schedule resolves from the shipped priors — byte-identical
    # to the pinned era (tests/test_tune.py parity gate).
    from tpu_mpi_tests.tune import cache as _tc, registry as _tr

    bench_tune = os.environ.get("TPU_MPI_BENCH_TUNE", "").lower() not in (
        "", "0", "false"
    )
    cache_path = _tc.default_cache_path()
    if bench_tune or os.path.exists(cache_path):
        budget = os.environ.get("TPU_MPI_TUNE_BUDGET")
        _tr.configure(
            cache_path=cache_path,
            enabled=bench_tune,
            budget_s=float(budget) if budget else None,
        )
    # temporal blocking: k timesteps per HBM pass over deep (k·2-wide)
    # halos — interior-identical to per-step exchange (tested in
    # tests/test_pallas.py::test_iterate_multistep_*); the exchanged volume
    # per timestep is unchanged, messages drop k-fold. Explicit
    # TPU_MPI_BENCH_STEPS > cached winner > prior (4).
    steps = _resolve_steps(
        os.environ.get("TPU_MPI_BENCH_STEPS"), n=n, world=world
    )

    rec = {"metric": "stencil2d_fullstep_8192_iters_per_s"}
    rec.update(_measure(
        dtype_name, n=n, steps=steps, world=world, mesh=mesh,
        axis_name=axis_name, topo=topo,
        blocks_env=os.environ.get("TPU_MPI_BENCH_BLOCKS"),
        overlap_env=os.environ.get("TPU_MPI_BENCH_OVERLAP"),
        tier_env=os.environ.get("TPU_MPI_BENCH_TIER"),
    ))

    second = os.environ.get("TPU_MPI_BENCH_SECOND_DTYPE", "")
    if second in ("none", "0"):
        second_dtype = None
    elif second:
        if second not in ("float32", "bfloat16"):
            # same contract as the primary knob: a typo must fail, not
            # record a mislabeled float32 run into the round artifact
            raise SystemExit(
                f"TPU_MPI_BENCH_SECOND_DTYPE={second!r} unsupported "
                "(float32 | bfloat16 | none | 0)"
            )
        second_dtype = second
    else:
        second_dtype = "bfloat16" if dtype_name == "float32" else "float32"
    if second_dtype == dtype_name:
        # explicit-but-redundant request: say so rather than silently
        # dropping the sub-object (stdout stays the one JSON line)
        from tpu_mpi_tests.drivers._common import decline_note

        decline_note(
            f"TPU_MPI_BENCH_SECOND_DTYPE={second!r} equals the "
            "primary dtype; no second measurement"
        )
    elif second_dtype:
        # same process, back-to-back → same contention window as the
        # primary to first order; the sub-object mirrors the top-level
        # field shapes so both headlines parse identically
        rec[second_dtype] = _measure(
            second_dtype, n=n, steps=steps, world=world, mesh=mesh,
            axis_name=axis_name, topo=topo, blocks_env=None,
        )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
