// tpumt_run — native multi-process launcher (≅ mpirun/jsrun for this
// framework's local multi-process mode; the shell twin is
// tpu/run_local_multiproc.sh).
//
// Spawns N copies of a command with the jax.distributed coordination env
// (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) wired per
// child, waits for all, and returns nonzero if any child failed — the same
// contract mpirun gives the reference's launch scripts
// (/root/reference/jlse/run.sh:29-33).
//
// Usage: tpumt_run -n NPROCS [-p PORT] [-o PREFIX] [-t SECONDS] -- command
//        [args...]
//
// -o PREFIX redirects each child's stdout+stderr to PREFIX<rank>.txt
// (≅ the per-run `out-<tag>.txt` redirection of the reference's launch
// scripts, /root/reference/summit/run.sh:31 — and what mpirun's
// --output-filename gives; without it parallel children interleave lines).
//
// -t SECONDS arms a launcher-level deadline: if any rank is still running
// when it expires, every child is killed (SIGKILL to the process group) and
// the launcher exits 124 — the batch-scheduler walltime role
// (≅ job.lsf/job.pbs walltime limits) for local runs, so a rank hung in a
// dead collective cannot wedge the launcher forever. Pairs with the
// in-process Python watchdog (instrument/watchdog.py), which attributes the
// hang; this is the backstop when a process is too wedged to self-report.

#include <cerrno>
#include <fcntl.h>
#include <signal.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {
pid_t g_pids[4096];
int g_npids = 0;
volatile sig_atomic_t g_timed_out = 0;

void on_alarm(int) {
  g_timed_out = 1;
  for (int i = 0; i < g_npids; ++i) {
    pid_t pid = g_pids[i];
    if (pid <= 0) continue;    // already reaped; pid may be recycled
    kill(-pid, SIGKILL);       // whole process group (async-signal-safe)
    kill(pid, SIGKILL);        // fallback if the child hadn't setpgid yet
  }
}
}  // namespace

int main(int argc, char** argv) {
  int nprocs = 0;
  int port = 0;
  int timeout_s = 0;
  int cmd_start = -1;
  const char* out_prefix = nullptr;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-n") == 0 && i + 1 < argc) {
      nprocs = std::atoi(argv[++i]);
    } else if (std::strcmp(argv[i], "-p") == 0 && i + 1 < argc) {
      port = std::atoi(argv[++i]);
    } else if (std::strcmp(argv[i], "-o") == 0 && i + 1 < argc) {
      out_prefix = argv[++i];
    } else if (std::strcmp(argv[i], "-t") == 0 && i + 1 < argc) {
      char* end = nullptr;
      long v = std::strtol(argv[++i], &end, 10);
      if (end == argv[i] || *end != '\0' || v < 1 || v > 86400 * 365) {
        std::fprintf(stderr, "tpumt_run: -t wants seconds >= 1, got %s\n",
                     argv[i]);
        return 2;
      }
      timeout_s = static_cast<int>(v);
    } else if (std::strcmp(argv[i], "--") == 0) {
      cmd_start = i + 1;
      break;
    } else {
      std::fprintf(stderr, "tpumt_run: unknown arg %s\n", argv[i]);
      return 2;
    }
  }
  if (nprocs < 1 || nprocs > 4096 || cmd_start < 0 || cmd_start >= argc) {
    std::fprintf(
        stderr,
        "usage: tpumt_run -n NPROCS [-p PORT] [-o PREFIX] [-t SECONDS] -- "
        "command [args...]\n");
    return 2;
  }
  if (port == 0) {
    port = 10000 + static_cast<int>(getpid() % 20000);
  }
  std::string coord = "localhost:" + std::to_string(port);

  std::vector<pid_t> pids;
  for (int rank = 0; rank < nprocs; ++rank) {
    pid_t pid = fork();
    if (pid < 0) {
      std::perror("tpumt_run: fork");
      // already-forked ranks would otherwise run orphaned forever, blocked
      // waiting for peers that will never arrive — kill their groups
      for (pid_t p : pids) {
        kill(-p, SIGKILL);
        kill(p, SIGKILL);
        waitpid(p, nullptr, 0);
      }
      return 1;
    }
    if (pid == 0) {
      setpgid(0, 0);  // own group, so the deadline can kill grandchildren
      setenv("JAX_COORDINATOR_ADDRESS", coord.c_str(), 1);
      setenv("JAX_NUM_PROCESSES", std::to_string(nprocs).c_str(), 1);
      setenv("JAX_PROCESS_ID", std::to_string(rank).c_str(), 1);
      if (out_prefix != nullptr) {
        std::string path = std::string(out_prefix) + std::to_string(rank) +
                           ".txt";
        int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
        if (fd < 0) {
          std::perror("tpumt_run: open out file");
          _exit(127);
        }
        dup2(fd, 1);
        dup2(fd, 2);
        if (fd > 2) close(fd);
      }
      execvp(argv[cmd_start], &argv[cmd_start]);
      std::perror("tpumt_run: execvp");
      _exit(127);
    }
    pids.push_back(pid);
    g_pids[g_npids++] = pid;
  }

  if (timeout_s > 0) {
    signal(SIGALRM, on_alarm);
    alarm(static_cast<unsigned>(timeout_s));
  }

  int rc = 0;
  for (size_t i = 0; i < pids.size(); ++i) {
    pid_t pid = pids[i];
    int status = 0;
    pid_t r;
    do {  // SIGALRM interrupts waitpid; retry so every child is reaped
      r = waitpid(pid, &status, 0);
    } while (r < 0 && errno == EINTR);
    g_pids[i] = -1;  // reaped: the pid may be recycled, never signal it
    if (r < 0) {
      std::perror("tpumt_run: waitpid");
      rc = 1;
    } else if (WIFEXITED(status) && WEXITSTATUS(status) != 0) {
      rc = WEXITSTATUS(status);
    } else if (WIFSIGNALED(status)) {
      std::fprintf(stderr, "tpumt_run: child %d killed by signal %d\n",
                   static_cast<int>(pid), WTERMSIG(status));
      rc = 128 + WTERMSIG(status);
    }
  }
  alarm(0);
  if (g_timed_out) {
    std::fprintf(stderr,
                 "tpumt_run: deadline of %d s exceeded; killed all ranks\n",
                 timeout_s);
    return 124;
  }
  return rc;
}
