// tpumt_run — native multi-process launcher (≅ mpirun/jsrun for this
// framework's local multi-process mode; the shell twin is
// tpu/run_local_multiproc.sh).
//
// Spawns N copies of a command with the jax.distributed coordination env
// (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) wired per
// child, waits for all, and returns nonzero if any child failed — the same
// contract mpirun gives the reference's launch scripts
// (/root/reference/jlse/run.sh:29-33).
//
// Usage: tpumt_run -n NPROCS [-p PORT] [-o PREFIX] -- command [args...]
//
// -o PREFIX redirects each child's stdout+stderr to PREFIX<rank>.txt
// (≅ the per-run `out-<tag>.txt` redirection of the reference's launch
// scripts, /root/reference/summit/run.sh:31 — and what mpirun's
// --output-filename gives; without it parallel children interleave lines).

#include <fcntl.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

int main(int argc, char** argv) {
  int nprocs = 0;
  int port = 0;
  int cmd_start = -1;
  const char* out_prefix = nullptr;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-n") == 0 && i + 1 < argc) {
      nprocs = std::atoi(argv[++i]);
    } else if (std::strcmp(argv[i], "-p") == 0 && i + 1 < argc) {
      port = std::atoi(argv[++i]);
    } else if (std::strcmp(argv[i], "-o") == 0 && i + 1 < argc) {
      out_prefix = argv[++i];
    } else if (std::strcmp(argv[i], "--") == 0) {
      cmd_start = i + 1;
      break;
    } else {
      std::fprintf(stderr, "tpumt_run: unknown arg %s\n", argv[i]);
      return 2;
    }
  }
  if (nprocs < 1 || cmd_start < 0 || cmd_start >= argc) {
    std::fprintf(
        stderr,
        "usage: tpumt_run -n NPROCS [-p PORT] [-o PREFIX] -- command "
        "[args...]\n");
    return 2;
  }
  if (port == 0) {
    port = 10000 + static_cast<int>(getpid() % 20000);
  }
  std::string coord = "localhost:" + std::to_string(port);

  std::vector<pid_t> pids;
  for (int rank = 0; rank < nprocs; ++rank) {
    pid_t pid = fork();
    if (pid < 0) {
      std::perror("tpumt_run: fork");
      return 1;
    }
    if (pid == 0) {
      setenv("JAX_COORDINATOR_ADDRESS", coord.c_str(), 1);
      setenv("JAX_NUM_PROCESSES", std::to_string(nprocs).c_str(), 1);
      setenv("JAX_PROCESS_ID", std::to_string(rank).c_str(), 1);
      if (out_prefix != nullptr) {
        std::string path = std::string(out_prefix) + std::to_string(rank) +
                           ".txt";
        int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
        if (fd < 0) {
          std::perror("tpumt_run: open out file");
          _exit(127);
        }
        dup2(fd, 1);
        dup2(fd, 2);
        if (fd > 2) close(fd);
      }
      execvp(argv[cmd_start], &argv[cmd_start]);
      std::perror("tpumt_run: execvp");
      _exit(127);
    }
    pids.push_back(pid);
  }

  int rc = 0;
  for (pid_t pid : pids) {
    int status = 0;
    if (waitpid(pid, &status, 0) < 0) {
      std::perror("tpumt_run: waitpid");
      rc = 1;
    } else if (WIFEXITED(status) && WEXITSTATUS(status) != 0) {
      rc = WEXITSTATUS(status);
    } else if (WIFSIGNALED(status)) {
      std::fprintf(stderr, "tpumt_run: child %d killed by signal %d\n",
                   static_cast<int>(pid), WTERMSIG(status));
      rc = 128 + WTERMSIG(status);
    }
  }
  return rc;
}
