// libtpumt — native monotonic clock + accumulating phase timers.
//
// The reference's timing primitive is clock_gettime(CLOCK_MONOTONIC) read
// around each hot-loop iteration (mpi_stencil_gt.cc:200-204,
// mpi_stencil2d_gt.cc:512-526) and MPI_Wtime phase brackets
// (mpi_daxpy_nvtx.cc:168,242-291). This library is the same primitive for
// the TPU framework's host side, loaded via ctypes
// (tpu_mpi_tests/instrument/native_time.py): a raw monotonic nanosecond
// clock plus a small slot-based accumulator so repeated phase brackets cost
// two calls and no Python arithmetic.

#include <cstdint>
#include <ctime>

namespace {

constexpr int kMaxSlots = 64;

struct Slot {
  double accum_s;
  double started_at;
  std::int64_t count;
  int running;
};

Slot g_slots[kMaxSlots];

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + 1e-9 * ts.tv_nsec;
}

}  // namespace

extern "C" {

// Raw CLOCK_MONOTONIC in nanoseconds (≅ the reference's timespec reads).
std::int64_t tpumt_monotonic_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<std::int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

// Slot-based accumulating phase timers; slot ∈ [0, 64).
int tpumt_phase_start(int slot) {
  if (slot < 0 || slot >= kMaxSlots) return -1;
  g_slots[slot].started_at = now_s();
  g_slots[slot].running = 1;
  return 0;
}

int tpumt_phase_stop(int slot) {
  if (slot < 0 || slot >= kMaxSlots || !g_slots[slot].running) return -1;
  g_slots[slot].accum_s += now_s() - g_slots[slot].started_at;
  g_slots[slot].count += 1;
  g_slots[slot].running = 0;
  return 0;
}

double tpumt_phase_seconds(int slot) {
  if (slot < 0 || slot >= kMaxSlots) return -1.0;
  return g_slots[slot].accum_s;
}

std::int64_t tpumt_phase_count(int slot) {
  if (slot < 0 || slot >= kMaxSlots) return -1;
  return g_slots[slot].count;
}

int tpumt_phase_reset(int slot) {
  if (slot < 0 || slot >= kMaxSlots) return -1;
  g_slots[slot] = Slot{};
  return 0;
}

}  // extern "C"
