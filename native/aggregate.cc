// tpumt_avg — native result aggregator (≅ avg.sh, /root/reference/avg.sh:1-15).
//
// The reference greps a pattern in every out-*.txt and awk-averages the
// ':'-delimited second field. This tool keeps that exact contract (default
// pattern "gather", field 2, per-file mean) and extends it with min/max/count
// stats and JSONL key extraction, as a single static binary so aggregation
// works on TPU-VM workers without a Python environment.
//
// Usage:
//   tpumt_avg [-p PATTERN] [-k JSON_KEY] [-s] file.txt [file2.txt ...]
//     -p PATTERN   substring to select lines (default: "gather")
//     -k KEY       extract `"KEY": <number>` from matching JSONL lines
//                  instead of the ':'-delimited field
//     -s           print min/max/count alongside the mean

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <limits>
#include <string>
#include <vector>

namespace {

struct Stats {
  double sum = 0.0;
  double mn = std::numeric_limits<double>::infinity();
  double mx = -std::numeric_limits<double>::infinity();
  long count = 0;

  void add(double v) {
    sum += v;
    mn = std::min(mn, v);
    mx = std::max(mx, v);
    ++count;
  }
};

// Field 2 of a ':'-delimited line, like `awk -F: '{ ... $2 ... }'`.
bool parse_colon_field(const std::string& line, double* out) {
  auto pos = line.find(':');
  if (pos == std::string::npos) return false;
  auto rest = line.substr(pos + 1);
  auto next = rest.find(':');
  if (next != std::string::npos) rest = rest.substr(0, next);
  char* end = nullptr;
  double v = std::strtod(rest.c_str(), &end);
  if (end == rest.c_str()) return false;
  *out = v;
  return true;
}

// `"key": <number>` anywhere in the line (naive but dependency-free; our
// JSONL records are flat, emitted by instrument/report.py).
bool parse_json_key(const std::string& line, const std::string& key,
                    double* out) {
  const std::string needle = "\"" + key + "\":";
  auto pos = line.find(needle);
  if (pos == std::string::npos) return false;
  const char* start = line.c_str() + pos + needle.size();
  char* end = nullptr;
  double v = std::strtod(start, &end);
  if (end == start) return false;
  *out = v;
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  std::string pattern = "gather";
  std::string json_key;
  bool stats = false;
  std::vector<std::string> files;

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg == "-p" && i + 1 < argc) {
      pattern = argv[++i];
    } else if (arg == "-k" && i + 1 < argc) {
      json_key = argv[++i];
    } else if (arg == "-s") {
      stats = true;
    } else if (arg == "-h" || arg == "--help") {
      std::fprintf(stderr,
                   "usage: %s [-p PATTERN] [-k JSON_KEY] [-s] files...\n",
                   argv[0]);
      return 0;
    } else {
      files.push_back(arg);
    }
  }
  if (files.empty()) {
    std::fprintf(stderr, "tpumt_avg: no input files\n");
    return 1;
  }

  std::printf("PATTERN=%s\n", pattern.c_str());
  int rc = 0;
  for (const auto& path : files) {
    std::ifstream in(path);
    if (!in) {
      std::fprintf(stderr, "tpumt_avg: cannot open %s\n", path.c_str());
      rc = 1;
      continue;
    }
    Stats st;
    std::string line;
    while (std::getline(in, line)) {
      if (line.find(pattern) == std::string::npos) continue;
      double v;
      bool ok = json_key.empty() ? parse_colon_field(line, &v)
                                 : parse_json_key(line, json_key, &v);
      if (ok) st.add(v);
    }
    if (st.count == 0) {
      std::printf("%s no-matches\n", path.c_str());
      continue;
    }
    if (stats) {
      std::printf("%s %g min=%g max=%g n=%ld\n", path.c_str(),
                  st.sum / st.count, st.mn, st.mx, st.count);
    } else {
      std::printf("%s %g\n", path.c_str(), st.sum / st.count);
    }
  }
  return rc;
}
