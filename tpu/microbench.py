#!/usr/bin/env python
"""Microbenchmark runner: reproduces every measured row in BASELINE.md.

Usage (from /root/repo):
    python tpu/microbench.py [daxpy] [stencil] [iterate] [ceiling]

Runs the selected groups (default: all) on whatever backend is active and
prints one JSON line per measurement plus a summary table. Timing uses the
sync-honest discipline of ``instrument/timers``: device-side chained loops
with difference timing (``iterate``), or large-N dispatch differencing
(``dispatch_rate``) for ops that cannot chain.
"""

from __future__ import annotations

import functools
import json
import sys
import time


def _emit(results, metric, value, unit, detail=""):
    rec = {"metric": metric, "value": round(value, 3), "unit": unit}
    if detail:
        rec["detail"] = detail
    print(json.dumps(rec), flush=True)
    results.append(rec)


def bench_daxpy(results):
    import jax.numpy as jnp

    from tpu_mpi_tests.instrument.timers import dispatch_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.daxpy import daxpy, init_xy

    for logn in (24, 26):
        n = 1 << logn
        x, y = init_xy(n, jnp.float32)
        gb = 3 * 4 * n / 1e9
        t = dispatch_rate(
            lambda a, b: daxpy(2.0, a, b), x, y, n_iter=1000, n_base=100
        )
        _emit(results, f"daxpy_xla_2^{logn}_gbps", gb / t, "GB/s")
        t = dispatch_rate(
            lambda a, b: PK.daxpy_pallas(2.0, a, b), x, y,
            n_iter=1000, n_base=100,
        )
        _emit(results, f"daxpy_pallas_2^{logn}_gbps", gb / t, "GB/s")


def bench_stencil(results):
    import numpy as np

    import jax.numpy as jnp

    from tpu_mpi_tests.instrument.timers import dispatch_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.stencil import stencil2d_1d_5_jit

    z = jnp.asarray(
        np.random.default_rng(2)
        .normal(size=(1028, 8192))
        .astype(np.float32)
    )
    for dim in (0, 1):
        out_elts = (1024 * 8192) if dim == 0 else (1028 * 8188)
        gb = out_elts * 4 * 2 / 1e9  # 2-pass model
        t = dispatch_rate(
            lambda a: stencil2d_1d_5_jit(a, 3.0, dim=dim), z,
            n_iter=500, n_base=50,
        )
        _emit(results, f"stencil_xla_d{dim}_eff_gbps", gb / t, "GB/s",
              "1028x8192 f32, 2-pass traffic model")
        t = dispatch_rate(
            lambda a: PK.stencil2d_pallas(a, 3.0, dim=dim, tile=512), z,
            n_iter=500, n_base=50,
        )
        _emit(results, f"stencil_pallas_d{dim}_eff_gbps", gb / t, "GB/s",
              "1028x8192 f32, 2-pass traffic model")


def bench_iterate(results):
    import jax
    import numpy as np

    from tpu_mpi_tests.arrays.domain import Domain2D
    from tpu_mpi_tests.comm.collectives import device_init
    from tpu_mpi_tests.comm.halo import iterate_pallas_fn
    from tpu_mpi_tests.comm.mesh import make_mesh, topology
    from tpu_mpi_tests.instrument.timers import block
    from tpu_mpi_tests.kernels.stencil import analytic_pairs

    n = 8192
    topo = topology()
    world = topo.global_device_count
    if n % world:
        return
    mesh = make_mesh()
    d = Domain2D(
        n_local_deriv=n // world, n_global_other=n, n_shards=world, dim=1
    )
    f, _ = analytic_pairs()["2d_dim1"]

    for dtype, bits in (("float32", 4), ("bfloat16", 2)):
        import jax.numpy as jnp

        dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype]
        zg = device_init(
            mesh, lambda r: d.init_shard_jax(f, r, dt), axis=1
        )
        run = iterate_pallas_fn(mesh, mesh.axis_names[0], d.n_bnd, 1e-6)
        zg = block(run(zg, 3))
        t0 = time.perf_counter()
        zg = block(run(zg, 100))
        t_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        zg = block(run(zg, 1100))
        t_l = time.perf_counter() - t0
        per = (t_l - t_s) / 1000
        _emit(results, f"iterate_{dtype}_iters_per_s", 1 / per, "iter/s",
              f"{n}x{n}, {n * n * bits * 2 / per / 1e9:.0f} GB/s")


def bench_ceiling(results):
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpu_mpi_tests.instrument.timers import dispatch_rate

    z = jnp.asarray(
        np.random.default_rng(0)
        .normal(size=(8192, 8192))
        .astype(np.float32)
    )
    f = jax.jit(lambda a: a * 2.0 + a)
    t = dispatch_rate(f, z, n_iter=500, n_base=50)
    _emit(results, "hbm_ceiling_probe_gbps",
          8192 * 8192 * 4 * 2 / t / 1e9, "GB/s",
          "fused 2-op elementwise, 8192^2 f32")


GROUPS = {
    "daxpy": bench_daxpy,
    "stencil": bench_stencil,
    "iterate": bench_iterate,
    "ceiling": bench_ceiling,
}


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:]) or list(GROUPS)
    unknown = [a for a in args if a not in GROUPS]
    if unknown:
        print(f"unknown groups {unknown}; valid: {list(GROUPS)}",
              file=sys.stderr)
        return 2
    results = []
    for g in args:
        GROUPS[g](results)
    width = max(len(r["metric"]) for r in results) if results else 0
    print("-" * (width + 20))
    for r in results:
        print(f"{r['metric']:<{width}}  {r['value']:>10} {r['unit']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
