#!/usr/bin/env python
"""Microbenchmark runner: reproduces every measured row in BASELINE.md.

Usage (from /root/repo):
    python tpu/microbench.py [daxpy] [stencil] [iterate] [splitfused]
                             [ceiling] [attention] [heat] [blocks] [causal]
                             [streams] [vpu] [stripebalance] [stripeskip]
                             [roofline2]

Runs the selected groups (default: all) on whatever backend is active and
prints one JSON line per measurement plus a summary table. Timing uses the
sync-honest discipline of ``instrument/timers``: device-side chained loops
with difference timing (``iterate``), or large-N dispatch differencing
(``dispatch_rate``) for ops that cannot chain.
"""

from __future__ import annotations

import functools
import json
import os
import sys

# runnable from any cwd (the package lives beside this file's parent dir)
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _emit(results, metric, value, unit, detail=""):
    rec = {"metric": metric, "value": round(value, 3), "unit": unit}
    if detail:
        rec["detail"] = detail
    print(json.dumps(rec), flush=True)
    results.append(rec)


def bench_daxpy(results):
    import jax.numpy as jnp

    from tpu_mpi_tests.instrument.timers import dispatch_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.daxpy import daxpy, init_xy

    for logn in (24, 26, 28):
        n = 1 << logn
        x, y = init_xy(n, jnp.float32)
        gb = 3 * 4 * n / 1e9
        # fewer iters at 2^28 keeps device time ~2 s (plenty of signal)
        iters = 1000 if logn < 28 else 500
        t = dispatch_rate(
            lambda a, b: daxpy(2.0, a, b), x, y,
            n_iter=iters, n_base=iters // 10,
        )
        _emit(results, f"daxpy_xla_2^{logn}_gbps", gb / t, "GB/s")
        t = dispatch_rate(
            lambda a, b: PK.daxpy_pallas(2.0, a, b), x, y,
            n_iter=iters, n_base=iters // 10,
        )
        _emit(results, f"daxpy_pallas_2^{logn}_gbps", gb / t, "GB/s")
        del x, y

    # chained (fori_loop-carried) A/B: sustained streaming REQUIRES the
    # output aliased onto y — the out-of-place form churns a fresh carry
    # buffer per iteration (BASELINE.md aliasing-requirement row)
    import functools

    import jax
    from jax import lax

    from tpu_mpi_tests.instrument.timers import chain_rate

    n = 1 << 26
    gb = 3 * 4 * n / 1e9
    for inplace in (False, True):
        x, y = init_xy(n, jnp.float32)

        @functools.partial(jax.jit, donate_argnums=1)
        def run(xx, yy, n_iter, inplace=inplace):
            def body(_, cur):
                return PK.daxpy_pallas(1e-7, xx, cur, inplace=inplace)

            return lax.fori_loop(
                0, jnp.asarray(n_iter, jnp.int32), body, yy
            )

        per, _ = chain_rate(
            functools.partial(run, x), y, n_short=100, n_long=1100
        )
        _emit(
            results,
            f"daxpy_chained_{'aliased' if inplace else 'outofplace'}_gbps",
            gb / per, "GB/s", "2^26 f32, 1000-iter fori_loop carry",
        )
        del x, y


def bench_stencil(results):
    import numpy as np

    import jax.numpy as jnp

    from tpu_mpi_tests.instrument.timers import dispatch_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.stencil import stencil2d_1d_5_jit

    z = jnp.asarray(
        np.random.default_rng(2)
        .normal(size=(1028, 8192))
        .astype(np.float32)
    )
    for dim in (0, 1):
        out_elts = (1024 * 8192) if dim == 0 else (1028 * 8188)
        gb = out_elts * 4 * 2 / 1e9  # 2-pass model
        t = dispatch_rate(
            lambda a: stencil2d_1d_5_jit(a, 3.0, dim=dim), z,
            n_iter=500, n_base=50,
        )
        _emit(results, f"stencil_xla_d{dim}_eff_gbps", gb / t, "GB/s",
              "1028x8192 f32, 2-pass model; PER-DISPATCH — contention-noisy "
              "on shared chips, prefer the chained iterate rows")
        t = dispatch_rate(
            lambda a: PK.stencil2d_pallas(a, 3.0, dim=dim, tile=512), z,
            n_iter=500, n_base=50,
        )
        _emit(results, f"stencil_pallas_d{dim}_eff_gbps", gb / t, "GB/s",
              "1028x8192 f32, 2-pass model; PER-DISPATCH — contention-noisy "
              "on shared chips, prefer the chained iterate rows")


def _iterate_setup(n: int = 8192, dim: int = 1, n_local: int | None = None,
                   n_bnd: int = 2):
    """Shared mesh/domain/init plumbing for the chained benchmark groups.

    Returns ``(mesh, ax, d, make_z)`` or None when the domain does not
    divide over the available devices; ``make_z(dtype)`` builds a freshly
    device-initialized ghosted sharded array."""
    from tpu_mpi_tests.arrays.domain import Domain2D
    from tpu_mpi_tests.comm.collectives import device_init
    from tpu_mpi_tests.comm.mesh import make_mesh, topology
    from tpu_mpi_tests.kernels.stencil import analytic_pairs

    world = topology().global_device_count
    if n_local is None:
        if n % world:
            return None
        n_local = n // world
    mesh = make_mesh()
    d = Domain2D(
        n_local_deriv=n_local, n_global_other=n, n_shards=world, dim=dim,
        n_bnd=n_bnd,
    )
    f, _ = analytic_pairs()[f"2d_dim{dim}"]

    def make_z(dtype):
        return device_init(
            mesh, lambda r: d.init_shard_jax(f, r, dtype), axis=dim
        )

    return mesh, mesh.axis_names[0], d, make_z


def bench_iterate(results):
    """Chained in-place iterate rows — the kernel-only BASELINE metrics
    (robust to shared-chip contention; round-2 methodology note)."""
    import jax.numpy as jnp

    from tpu_mpi_tests.comm.halo import iterate_fused_fn, iterate_pallas_fn
    from tpu_mpi_tests.instrument.timers import chain_rate

    n = 8192
    setup = _iterate_setup(n, dim=1)
    if setup is None:
        return
    mesh, ax, d1, make_z1 = setup
    # dim 1 (lane shifts), pallas f32/bf16 + XLA f32 — 8192² domain
    for dtype, bits in (("float32", 4), ("bfloat16", 2)):
        dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype]
        zg = make_z1(dt)
        run = iterate_pallas_fn(mesh, ax, d1.n_bnd, 1e-6)
        per, zg = chain_rate(run, zg)
        _emit(results, f"iterate_d1_pallas_{dtype}_iters_per_s", 1 / per,
              "iter/s", f"{n}x{n}, {n * n * bits * 2 / per / 1e9:.0f} GB/s")
        del zg
    # temporal blocking (steps timesteps per HBM pass over deep halos):
    # the bench.py headline path
    from tpu_mpi_tests.kernels.stencil import N_BND

    steps = 4
    setup_k = _iterate_setup(n, dim=1, n_bnd=N_BND * steps)
    if setup_k is not None:
        mesh_k, ax_k, dk, make_zk = setup_k
        for dtype, bits in (("float32", 4), ("bfloat16", 2)):
            dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype]
            zg = make_zk(dt)
            run = iterate_pallas_fn(mesh_k, ax_k, dk.n_bnd, 1e-6,
                                    steps=steps)
            per, zg = chain_rate(run, zg, n_short=25, n_long=525)
            per /= steps
            _emit(
                results,
                f"iterate_d1_pallas_{dtype}_k{steps}_iters_per_s",
                1 / per, "iter/s",
                f"{n}x{n}, {steps}-step temporal blocking, "
                f"{n * n * bits * 2 / steps / per / 1e9:.0f} GB/s "
                "effective",
            )
            del zg
    zg = make_z1(jnp.float32)
    per, zg = chain_rate(
        iterate_fused_fn(mesh, ax, 1, 2, d1.n_bnd, 1.0, 1e-6), zg
    )
    _emit(results, "iterate_d1_xla_float32_iters_per_s", 1 / per, "iter/s",
          f"{n}x{n}, {n * n * 4 * 2 / per / 1e9:.0f} GB/s")
    del zg

    # dim 0 (sublane shifts) at the reference shard geometry 1028×8192
    mesh, ax, d0, make_z0 = _iterate_setup(n, dim=0, n_local=1024)
    elts = (1024 + 4) * n
    for name, mk in (
        ("pallas", lambda: iterate_pallas_fn(mesh, ax, d0.n_bnd, 1e-6,
                                             axis=0)),
        ("xla", lambda: iterate_fused_fn(mesh, ax, 0, 2, d0.n_bnd, 1.0,
                                         1e-6)),
    ):
        zg = make_z0(jnp.float32)
        per, zg = chain_rate(mk(), zg)
        _emit(results, f"iterate_d0_{name}_float32_iters_per_s", 1 / per,
              "iter/s",
              f"1028x{n}, {elts * 4 * 2 / per / 1e9:.0f} GB/s")
        del zg
    # dim-0 temporal blocking (deep sublane-axis ghosts); explicit n_local
    # means _iterate_setup cannot return None here
    mesh0, ax0, d0k, make_z0k = _iterate_setup(
        n, dim=0, n_local=1024, n_bnd=N_BND * steps
    )
    zg = make_z0k(jnp.float32)
    run = iterate_pallas_fn(mesh0, ax0, d0k.n_bnd, 1e-6, axis=0, steps=steps)
    per, zg = chain_rate(run, zg, n_short=25, n_long=525)
    per /= steps
    _emit(results, f"iterate_d0_pallas_float32_k{steps}_iters_per_s",
          1 / per, "iter/s",
          f"(1024+{2 * N_BND * steps})x{n}, {steps}-step temporal blocking, "
          f"{1024 * n * 4 * 2 / steps / per / 1e9:.0f} GB/s effective")
    del zg


def bench_splitfused(results):
    """Split-vs-fused A/B (SURVEY §7 hard part 2): exchange + stencil with
    and without an optimization_barrier at the phase boundary, periodic
    self-ring so the exchange moves real data on one chip."""
    import jax.numpy as jnp

    from tpu_mpi_tests.comm.halo import iterate_fused_fn
    from tpu_mpi_tests.instrument.timers import chain_rate

    n = 8192
    setup = _iterate_setup(n, dim=1)
    if setup is None:
        return
    mesh, ax, d, make_z = setup
    for label, kw in (("fused", {}), ("split", {"split": True})):
        zg = make_z(jnp.float32)
        run = iterate_fused_fn(mesh, ax, 1, 2, d.n_bnd, 1.0, 1e-6,
                               periodic=True, **kw)
        per, zg = chain_rate(run, zg)
        _emit(results, f"exchange_stencil_{label}_us_per_iter", per * 1e6,
              "us/iter", f"{n}x{n} f32, periodic self-ring")
        del zg


def bench_ceiling(results):
    """Practical HBM ceiling by two-point overhead fit.

    A single raw streaming rate under-reports the ceiling: every kernel
    launch carries a fixed overhead (~100 µs through the tunneled runtime)
    charged to however few bytes that op moves, which is why round 1's small
    fused-elementwise probe (600 GB/s) landed *below* measured daxpy. Fix:
    measure two streams of different traffic at the same size — 2-pass scale
    and 3-pass daxpy — and solve

        t_daxpy = 3·b/B + τ,   t_scale = 2·b/B + τ

    for the true stream bandwidth B and per-kernel overhead τ. B is the
    ceiling every per-op row is compared against (raw rows sit below it by
    exactly their launch-overhead share; larger arrays amortize toward it).
    """
    import jax.numpy as jnp

    from tpu_mpi_tests.instrument.timers import dispatch_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.daxpy import init_xy

    n = 1 << 26
    b = 4 * n / 1e9  # GB per pass
    x, y = init_xy(n, jnp.float32)
    t3 = dispatch_rate(
        lambda a, c: PK.daxpy_pallas(2.0, a, c), x, y,
        n_iter=1000, n_base=100,
    )
    t2 = dispatch_rate(
        lambda a: PK.stream_scale_pallas(2.0, a), x,
        n_iter=1000, n_base=100,
    )
    _emit(results, "stream_daxpy_3pass_gbps", 3 * b / t3, "GB/s",
          "raw 3-pass probe, 2^26 f32")
    _emit(results, "stream_scale_2pass_gbps", 2 * b / t2, "GB/s",
          "raw 2-pass probe, 2^26 f32")
    raw3 = 3 * b / t3
    bw = b / (t3 - t2) if t3 > t2 else float("inf")
    tau = 3 * t2 - 2 * t3  # fitted per-kernel overhead
    # noise guard: t3 ~ t2 makes the fit blow up (5 us of jitter on the
    # 0.27 GB delta would claim ~50 TB/s) and tau < 0 (⇔ bw < raw3) means
    # the fitted "ceiling" sits below the raw row it must bound — both are
    # measurement noise, not HBM
    if t3 > t2 and raw3 <= bw <= 2 * raw3 and tau >= 0:
        _emit(results, "hbm_ceiling_fit_gbps", bw, "GB/s",
              f"two-point overhead fit; per-kernel overhead "
              f"{tau * 1e6:.0f} us")
    else:
        _emit(results, "hbm_ceiling_fit_gbps", raw3, "GB/s",
              "fit degenerate (noise outside [raw, 2x raw]); raw 3-pass rate")


def bench_attention(results):
    """Flash-vs-XLA local attention (the long-context building block,
    SURVEY §5.7): softmax(q·kᵀ/√d)·v at L=8192, d=128, chained with the
    output fed back as the next query so iterations are data-dependent."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.instrument.timers import chain_rate
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas

    L, d = 8192, 128
    flops = 4.0 * L * L * d  # two L×L×d matmuls per iteration

    def xla_attn(q, k, v):
        s = jnp.matmul(q, k.T) / (d**0.5)
        return jnp.matmul(jax.nn.softmax(s, axis=-1), v)

    for dtype in ("float32", "bfloat16"):
        dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype]
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(kk, (L, d), dt)
            for kk in jax.random.split(key, 3)
        )
        # both tiers at MXU-native (DEFAULT) matmul precision — the
        # throughput configuration; correctness tests use HIGHEST
        for name, attn in (
            ("flash", lambda q, k, v: flash_attention_pallas(
                q, k, v, precision=jax.lax.Precision.DEFAULT)),
            ("xla", xla_attn),
        ):
            @functools.partial(jax.jit, donate_argnums=0)
            def run(state, n_iter, attn=attn):
                def body(_, st):
                    qq, k, v = st
                    return attn(qq, k, v), k, v

                return lax.fori_loop(
                    0, jnp.asarray(n_iter, jnp.int32), body, state
                )

            # 1000-iteration delta: at the tuned kernel's ~0.26 ms/iter the
            # older 400-iter delta (~0.1 s) barely cleared host-timer noise
            per, state = chain_rate(run, (q, k, v), n_short=100, n_long=1100)
            q, k, v = state
            _emit(results, f"attention_{name}_{dtype}_tflops", flops / per
                  / 1e12, "TFLOP/s", f"L={L} d={d} softmax(qk^T)v")
        del q, k, v


def bench_streams(results):
    """Stream-count probe family (round 3, VERDICT r2 weak #4): chained
    aliased kernels at S = 2 (scale), 3 (daxpy), 4 (sum3) HBM streams
    over n=2^26 f32, plus a daxpy block-shape sweep. The linear fit
    t(S) = overhead + S·n·4/BW yields a MEASURED per-stream bandwidth;
    daxpy's ratio to the S=3 prediction answers whether its 0.92× gap is
    kernel tiling or the HBM's multi-stream behavior."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.instrument.timers import chain_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK

    n = 1 << 26
    nb = n * 4
    key = jax.random.PRNGKey(0)
    kw, kx = jax.random.split(key)
    w = jax.random.uniform(kw, (n,), jnp.float32, 1e-9, 2e-9)
    x = jax.random.uniform(kx, (n,), jnp.float32, 1e-9, 2e-9)

    def chain(fn, y0, *ops, iters=1000):
        # operands ride as explicit jit args — closure capture would embed
        # the 268 MB buffers as constants in the remote-compile payload
        # (the tunnel rejects it with HTTP 413)
        @functools.partial(jax.jit, donate_argnums=0)
        def run(y, n_iter, *ops_):
            def body(_, cur):
                return fn(cur, *ops_)

            return lax.fori_loop(0, jnp.asarray(n_iter, jnp.int32), body, y)

        per, _ = chain_rate(
            lambda y, n_it: run(y, n_it, *ops), y0,
            n_short=iters // 10, n_long=iters,
        )
        return per

    # a COMMON block shape across the family: only S may vary between the
    # fit's points, or the per-block pipeline cost (which differs with
    # block count) leaks into the fitted slope — 2048 rows is the largest
    # block the 4-buffer kernel fits in VMEM
    BR = 2048
    y0 = jnp.ones((n,), jnp.float32)
    times = {}
    # S=2: y = a·y aliased (read + write)
    times[2] = chain(
        lambda y: PK.stream_scale_pallas(
            1.0 + 1e-9, y, inplace=True, block_rows=BR), y0
    )
    _emit(results, "stream2_scale_gbps", 2 * nb / times[2] / 1e9, "GB/s",
          f"chained aliased y=a*y, 2^26 f32, {BR}-row blocks")
    # S=3: y = a·x + y aliased (the daxpy under test)
    y0 = jnp.ones((n,), jnp.float32)
    times[3] = chain(
        lambda y, xx: PK.daxpy_pallas(
            1.0, xx, y, inplace=True, block_rows=BR), y0, x
    )
    _emit(results, "stream3_daxpy_gbps", 3 * nb / times[3] / 1e9, "GB/s",
          f"chained aliased y=a*x+y, 2^26 f32, {BR}-row blocks")
    # S=4: y = w + x + y aliased (3 reads + 1 write)
    y0 = jnp.ones((n,), jnp.float32)
    times[4] = chain(
        lambda y, ww, xx: PK.stream_sum3_pallas(
            ww, xx, y, inplace=True, block_rows=BR), y0, w, x,
    )
    _emit(results, "stream4_sum3_gbps", 4 * nb / times[4] / 1e9, "GB/s",
          f"chained aliased y=w+x+y, 2^26 f32, {BR}-row blocks")
    # least-squares fit t(S) = oh + S·nb/BW over the 3 points
    import numpy as np

    S = np.array(sorted(times))
    t = np.array([times[int(s)] for s in S])
    slope, oh = np.polyfit(S, t, 1)
    bw = nb / slope / 1e9
    pred3 = oh + 3 * slope
    _emit(results, "stream_fit_per_stream_gbps", bw, "GB/s",
          f"t(S)=oh+S*nb/BW fit; oh={oh * 1e6:.0f} us; "
          f"daxpy/pred3={pred3 / times[3]:.3f}")

    # 4× the bytes, same kernel: if the S-fit's "overhead" were per-call
    # it would amortize to ~2% here; measured it scales ~with the grid
    # step count instead (per-block pipeline cost), so the sustained
    # GB/s stays put — the round-3 answer to "why 0.92×"
    n28 = 1 << 28
    x28 = jax.random.uniform(
        jax.random.PRNGKey(1), (n28,), jnp.float32, 1e-9, 2e-9
    )
    y0 = jnp.ones((n28,), jnp.float32)
    per = chain(
        lambda y, xx: PK.daxpy_pallas(1.0, xx, y, inplace=True),
        y0, x28, iters=300,
    )
    _emit(results, "stream3_daxpy_2^28_gbps", 3 * n28 * 4 / per / 1e9,
          "GB/s", "chained aliased, 4x bytes of the fit family")
    del x28, y0

    # daxpy block-shape sweep (does tiling cost the gap?)
    for br in (1024, 2048, 4096, 8192):
        y0 = jnp.ones((n,), jnp.float32)
        try:
            per = chain(
                lambda y, xx, br=br: PK.daxpy_pallas(
                    1.0, xx, y, inplace=True, block_rows=br), y0, x,
            )
        except Exception as e:  # noqa: BLE001 — report OOM shapes
            _emit(results, f"daxpy_block{br}_gbps", float("nan"), "GB/s",
                  f"failed: {type(e).__name__}")
            continue
        _emit(results, f"daxpy_block{br}_gbps", 3 * nb / per / 1e9, "GB/s")


def bench_causal(results):
    """Causal flash tile-skip A/B (round 3, VERDICT r2 weak #1): fully-
    masked k tiles are skipped, so causal should run ~half the wall time
    of non-causal (equal USEFUL TFLOP/s), on both kernel paths — resident
    K/V (L=8192) and streaming K/V (L=32768, the flagship long-context
    row). Emits useful TFLOP/s: causal counts half the dense flops."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.instrument.timers import chain_rate
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas

    d = 128
    for L, path in ((8192, "resident"), (32768, "stream")):
        key = jax.random.PRNGKey(0)
        q0, k0, v0 = (
            jax.random.normal(kk, (L, d), jnp.bfloat16)
            for kk in jax.random.split(key, 3)
        )
        iters = max(100, 800 * 8192 // L)
        # (causal?, skip_tile, tag): skip_tile=None resolves to the
        # measured-best default (0/coupled for self-causal geometry on
        # BOTH kernel paths); the decoupled 256 variant is its
        # same-window A/B partner — the causal pair ALTERNATES twice
        # back-to-back and the min is reported (contention only
        # inflates; round-4 separate-pass lesson). These A/Bs are what
        # MEASURED the coupled defaults (resident contig AND
        # _STREAM_SKIP_TILE_DEFAULT).
        variants = [(False, None, "full"), (True, None, "causal"),
                    (True, 256, "causal_decoupled"),
                    (True, None, "causal"),
                    (True, 256, "causal_decoupled")]
        # ONE jitted fn per unique config: redefining inside the
        # alternation loop would make the repeated arms recompile the
        # same program (jax.jit caches per wrapped-function object)
        runs = {}
        for causal, skt, _ in variants:
            if (causal, skt) in runs:
                continue

            @functools.partial(jax.jit, donate_argnums=0)
            def run(state, n_iter, causal=causal, skt=skt):
                def body(_, st):
                    qq, k, v = st
                    out = flash_attention_pallas(
                        qq, k, v, causal=causal, skip_tile=skt,
                        precision=jax.lax.Precision.DEFAULT,
                    )
                    return out, k, v

                return lax.fori_loop(
                    0, jnp.asarray(n_iter, jnp.int32), body, state
                )

            runs[(causal, skt)] = run
        readings: dict[str, list] = {}
        for causal, skt, tag in variants:
            per, state = chain_rate(
                runs[(causal, skt)], (q0, k0, v0),
                n_short=iters // 10, n_long=iters,
            )
            q0, k0, v0 = state
            readings.setdefault(tag, []).append((causal, per))
        for tag, reads in readings.items():
            causal = reads[0][0]
            pers = [p for _, p in reads]
            per = min(pers)
            useful = 4.0 * L * L * d * (0.5 if causal else 1.0)
            all_r = ",".join(f"{p * 1e3:.3f}" for p in pers)
            _emit(results, f"attn_{path}_{tag}_bf16_L{L}", per * 1e3,
                  "ms/attn",
                  f"useful {useful / per / 1e12:.1f} TFLOP/s"
                  + (f"; reads [{all_r}]" if len(pers) > 1 else ""))
        del q0, k0, v0


def bench_blocks(results):
    """The bench.py headline schedule in isolation: S=2 resident-block
    dim-0 k-step vs the dim-1 single-buffer kernel, same process/window
    (BASELINE headline row)."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.halo import (
        iterate_pallas_blocks_fn,
        iterate_pallas_fn,
        split_blocks,
    )
    from tpu_mpi_tests.instrument.timers import block, chain_rate
    from tpu_mpi_tests.kernels.stencil import N_BND

    steps, n, S = 4, 8192, 2
    K = N_BND * steps
    zf = np.random.default_rng(0).normal(
        size=(n + 2 * K, n)
    ).astype(np.float32) / 10
    run = iterate_pallas_blocks_fn(S, K, 1e-4, steps=steps)
    st = split_blocks(jnp.asarray(zf), S, K)
    # one explicit warm dispatch: the tunnel charges a one-time ~0.9 s
    # cost to the SECOND dispatch of an executable (bench_heat note);
    # this makes chain_rate's internal warm absorb it
    st = block(run(st, 1))
    sec, st = chain_rate(run, st, n_short=25, n_long=525)
    _emit(results, f"blocks_S{S}_dim0_k{steps}_{n}_iters_per_s",
          steps / sec, "iter/s", f"{n}x{n} f32, resident blocks")
    del st

    # round-3 sharded generalization on a world-1 mesh — the code path a
    # multi-chip bench run enters (shard_map-wrapped state tuple); the
    # same-window A/B vs the plain schedule above prices the wrapper
    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    runs = iterate_pallas_blocks_fn(
        S, K, 1e-4, steps=steps, mesh=mesh, axis_name="shard"
    )
    sts = split_blocks(jnp.asarray(zf), S, K, mesh=mesh)
    sts = block(runs(sts, 1))
    sec, sts = chain_rate(runs, sts, n_short=25, n_long=525)
    _emit(results, f"blocks_S{S}_sharded_w1_k{steps}_{n}_iters_per_s",
          steps / sec, "iter/s",
          f"{n}x{n} f32, sharded resident blocks, world=1 mesh")
    del sts
    z1 = np.random.default_rng(1).normal(
        size=(n, n + 2 * K)
    ).astype(np.float32) / 10
    run1 = iterate_pallas_fn(mesh, "shard", K, 1e-4, axis=1, steps=steps)
    z = jnp.asarray(z1)
    z = block(run1(z, 1))
    sec, z = chain_rate(run1, z, n_short=25, n_long=525)
    _emit(results, f"dim1_single_k{steps}_{n}_iters_per_s", steps / sec,
          "iter/s", f"{n}x{n} f32, single buffer")
    del z


def bench_heat(results):
    """heat2d mini-app update tiers (BASELINE heat2d row): XLA body vs the
    in-place row-streaming Pallas Laplacian, k ∈ {1, 4, 8} at 2048²,
    f32 and (round 4, under the calibrated VMEM fit) bf16. CAVEAT for
    the bf16 rows at this size: one
    k-group's device work (~24 µs at k=4) sits BELOW the ~100 µs
    per-call launch overhead, so single runs swing ~3× with the shared
    chip's contention (21k–61k steps/s observed at k=4) — treat them as
    floor-bound; bf16 heat at 4096² (5.6–6.8k steps/s) is the robust
    size (BASELINE round-4 strip re-sweep, incl. the reverted
    noise-based block-clamp note)."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.halo import heat_step2d_fn
    from tpu_mpi_tests.instrument.timers import block, chain_rate

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("x", "y"))
    n = 2048
    for kernel, dtype in (("xla", np.float32), ("pallas", np.float32),
                          ("pallas", jnp.bfloat16)):
        dname = jnp.dtype(dtype).name
        for k in (1, 4, 8):
            z0 = np.random.default_rng(0).normal(
                size=(n + 2 * k, n + 2 * k)
            ).astype(dtype) / np.asarray(10, dtype)
            run = heat_step2d_fn(
                mesh, "x", "y", k, 0.05, 0.05, steps=k, kernel=kernel
            )
            z = jnp.asarray(z0)
            # two warm calls: the axon tunnel charges a one-time ~0.9 s
            # post-compile cost to the SECOND dispatch of an executable,
            # which chain_rate's single built-in warm call would otherwise
            # eat inside its short measurement (flipping the delta
            # negative → NaN)
            z = block(run(z, 1))
            z = block(run(z, 1))
            sec, z = chain_rate(
                run, z, n_short=max(1, 40 // k), n_long=max(2, 2000 // k)
            )
            _emit(results, f"heat2d_{kernel}_{dname}_k{k}_2048_steps_per_s",
                  k / sec, "steps/s")
            del z


def bench_vpu(results):
    """VPU compute roofline for the k-step kernel (round 4, VERDICT r3
    next #3). Two measurements whose ratio answers "is 2600 iter/s
    parked or slow":

    1. in-VMEM op-rate probes (``vpu_probe_pallas``): per-rep cost of a
       pure fma mix and the EXACT step5 kernel body (both axes) on a
       (512, 512) f32 resident block, from a 3-point linear fit over
       per-mix reps triples (with a reported linearity check) — launch
       overhead and the two HBM passes live in the intercept, leaving
       the attainable VPU element rate for this op mix;
    2. the S=2 resident-block schedule's marginal per-step cost: fit
       t(k) = a + b·k over k ∈ {2,4,6,8} at 8192² — b is what one more
       timestep really costs with HBM amortized.

    The kernel's per-element step time (b / 8192²) over the probe's
    per-element rep time is the fraction of the VPU ceiling the headline
    reaches; the fma/step5 ratio separately prices the shifts + concat.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.comm.halo import (
        iterate_pallas_blocks_fn,
        split_blocks,
    )
    from tpu_mpi_tests.instrument.timers import block, chain_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.stencil import N_BND

    H = W = 512
    elems = H * W
    z0 = np.random.default_rng(0).normal(size=(H, W)).astype(np.float32)

    import functools

    def probe_per_call(mix, reps, dname, iters=400):
        @functools.partial(jax.jit, donate_argnums=0,
                           static_argnames=("reps",))
        def run(z, n_iter, reps):
            def body(_, cur):
                return PK.vpu_probe_pallas(cur, reps, mix)

            return lax.fori_loop(0, jnp.asarray(n_iter, jnp.int32), body, z)

        z = jnp.asarray(z0, dtype=dname)
        z = block(run(z, 1, reps=reps))
        per, _ = chain_rate(
            lambda zz, n_it: run(zz, n_it, reps=reps), z,
            n_short=iters // 10, n_long=iters,
        )
        return per

    # (nominal ops/elt, reps triple): rep counts sized so the per-rep
    # cost differences are hundreds of us — far above the shared chip's
    # contention noise (the first cut used 64/320 everywhere and the fma
    # delta was ~10 us: it measured noise, NaN rates). bf16 probes
    # (round 4) put a measured ceiling under the OFFICIAL bf16 headline's
    # claimed VPU plateau; its schedule is dim-1, so step5_d1 is the mix
    step5fma = os.environ.get("TPU_MPI_VPU_STEP5FMA", "") not in ("", "0")
    # insertion order IS measurement order (the loop below walks the
    # dict): each opt-in step5fma form A/B probe (round-5 diff-vs-fma —
    # BASELINE VPU note: the raw 4-tap se-folded form measured SLOWER on
    # every axis/dtype) sits immediately after its step5 counterpart, so
    # the two forms share one contention window per (axis, dtype) like
    # the recorded A/B did, instead of running in separate sequential
    # passes minutes apart on the shared chip
    PROBES = {}
    for dname in ("float32", "bfloat16"):
        PROBES[("fma", dname)] = (2, (512, 2048, 8192))
        PROBES[("step5_d0", dname)] = (7, (256, 1024, 4096))
        if step5fma:
            PROBES[("step5fma_d0", dname)] = (7, (256, 1024, 4096))
        PROBES[("step5_d1", dname)] = (7, (64, 256, 1024))
        if step5fma:
            PROBES[("step5fma_d1", dname)] = (7, (64, 256, 1024))
    probe_rate = {}
    for (mix, dname), (ops, reps3) in PROBES.items():
        ts = np.array([probe_per_call(mix, r, dname) for r in reps3])
        rarr = np.array(reps3, np.float64)
        per_rep, off = np.polyfit(rarr, ts, 1)
        # linearity gate: the middle point must sit on the 2-point line
        # through the ends, else the fit is contention-window garbage
        mid_pred = ts[0] + (ts[2] - ts[0]) * (rarr[1] - rarr[0]) / (
            rarr[2] - rarr[0]
        )
        lin = ts[1] / mid_pred
        if not (0.85 <= lin <= 1.15):
            # contention hit one of the three points: an invalid
            # measurement must LOOK invalid downstream (chain_rate's own
            # NaN convention), not ship a confident headline with the
            # anomaly buried in the detail string
            per_rep = float("nan")
        probe_rate[(mix, dname)] = elems / per_rep  # element-steps / s
        _emit(results, f"vpu_{mix}_{dname}_gops",
              elems * ops / per_rep / 1e9, "Gop/s",
              f"{H}x{W} {dname} resident; {per_rep / elems * 1e12:.2f} "
              f"ps/elt/rep; nominal {ops} ops/elt; reps={reps3}; "
              f"linearity {lin:.3f}")

    # the real schedule's marginal per-step cost (same geometry as the
    # headline: S=2 resident blocks, 8192^2 f32)
    n, S = 8192, 2
    ks = (2, 4, 6, 8)
    t_call = {}
    for k in ks:
        K = N_BND * k
        zf = np.random.default_rng(1).normal(
            size=(n + 2 * K, n)
        ).astype(np.float32) / 10
        run = iterate_pallas_blocks_fn(S, K, 1e-4, steps=k)
        st = split_blocks(jnp.asarray(zf), S, K)
        st = block(run(st, 1))
        sec, st = chain_rate(
            run, st, n_short=max(5, 50 // k), n_long=max(50, 2000 // k)
        )
        t_call[k] = sec
        _emit(results, f"vpu_kstep_S{S}_k{k}_iters_per_s", k / sec,
              "iter/s", f"{n}x{n} f32 resident blocks")
        del st

    karr = np.array(ks, np.float64)
    tarr = np.array([t_call[k] for k in ks])
    b, a = np.polyfit(karr, tarr, 1)
    kernel_rate = n * n / b  # element-steps / s
    frac = kernel_rate / probe_rate[("step5_d0", "float32")]
    _emit(results, "vpu_kstep_marginal_us", b * 1e6, "us/step",
          f"fit t(k)=a+b*k over k={ks}; a={a * 1e6:.0f} us; "
          f"implied plateau {1.0 / b:.0f} iter/s")
    _emit(results, "vpu_kstep_vs_probe_ceiling", frac, "ratio",
          "kernel element rate / step5_d0 in-VMEM probe rate "
          "(1.0 = the schedule reaches the measured VPU ceiling "
          "for its own op mix)")

    # the OFFICIAL bf16 headline schedule's marginal per-step cost:
    # dim-1 single buffer at 8192² bf16 (no mesh — the kernel alone),
    # against the bf16 step5_d1 probe ceiling
    t16 = {}
    for k in ks:
        K16 = N_BND * k
        z16 = np.random.default_rng(2).normal(
            size=(n, n + 2 * K16)
        ).astype(jnp.bfloat16) / np.asarray(10, jnp.bfloat16)

        @functools.partial(jax.jit, donate_argnums=0,
                           static_argnames=("k",))
        def run16(z, n_iter, k):
            def body(_, cur):
                return PK.stencil2d_iterate_pallas(
                    cur, 1e-4, dim=1, steps=k, phys_static=(1, 1)
                )

            return lax.fori_loop(0, jnp.asarray(n_iter, jnp.int32),
                                 body, z)

        z = jnp.asarray(z16)
        z = block(run16(z, 1, k=k))
        sec, z = chain_rate(
            lambda zz, n_it, k=k: run16(zz, n_it, k=k), z,
            n_short=max(5, 50 // k), n_long=max(50, 2000 // k),
        )
        t16[k] = sec
        _emit(results, f"vpu_kstep_bf16_d1_k{k}_iters_per_s", k / sec,
              "iter/s", f"{n}x{n} bf16 dim-1 single buffer")
        del z
    t16arr = np.array([t16[k] for k in ks])
    b16, a16 = np.polyfit(karr, t16arr, 1)
    frac16 = (n * n / b16) / probe_rate[("step5_d1", "bfloat16")]
    _emit(results, "vpu_kstep_bf16_marginal_us", b16 * 1e6, "us/step",
          f"fit over k={ks}; a={a16 * 1e6:.0f} us; implied plateau "
          f"{1.0 / b16:.0f} iter/s")
    _emit(results, "vpu_kstep_bf16_vs_probe_ceiling", frac16, "ratio",
          "bf16 dim-1 kernel element rate / bf16 step5_d1 in-VMEM "
          "probe rate")


def bench_roofline2(results):
    """Two-axis rooflines for the heat Laplacian and dual-dim hand tiers
    (round 5, VERDICT r4 #6): replace "N× faster than XLA" with "this
    close to the hardware" for the two kernels that only had XLA-relative
    ratios. Per kernel:

    - OPS axis: in-VMEM probe of the kernel's EXACT op mix
      (``vpu_probe_pallas`` ``heat5``/``dualdim`` mixes, 3-point
      linear fit per the round-4 ``vpu`` pattern);
    - BYTES axis: HBM passes × width over the 744 GB/s marginal stream
      rate (round-3 streams fit);
    - the kernel's own marginal cost: heat fits t(k)=a+b·k (k amortizes
      launch + HBM, b is pure per-step cost → compare to the ops axis);
      dual-dim is one-shot, so t(elems)=a+c·elems over 3 domain sizes
      (chained via ``z + eps·residual`` feedback, +2 HBM passes charged
      to the bytes axis) and c is compared against BOTH axes — the
      larger model time is the binding regime.

    Also (VERDICT r4 #5) the heat bf16 block-size A/B at a TALL 2048-wide
    domain (16384 rows: per-call work ~16× the 2048² rows' ~24 µs, far
    above the ~100 µs launch floor that made the round-4 A/B vacuous),
    B=128 vs 256 interleaved twice, min per arm.
    """
    import functools
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.halo import heat_step2d_fn
    from tpu_mpi_tests.instrument.timers import block, chain_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.stencil import N_BND

    STREAM_GBPS = 744.0  # round-3 marginal stream rate (BASELINE.md)
    H = W = 512
    elems = H * W
    z0 = np.random.default_rng(0).normal(size=(H, W)).astype(np.float32)

    def probe_per_call(mix, reps, dname, iters=400):
        @functools.partial(jax.jit, donate_argnums=0,
                           static_argnames=("reps",))
        def run(z, n_iter, reps):
            def body(_, cur):
                return PK.vpu_probe_pallas(cur, reps, mix)

            return lax.fori_loop(0, jnp.asarray(n_iter, jnp.int32), body, z)

        z = jnp.asarray(z0, dtype=dname)
        z = block(run(z, 1, reps=reps))
        per, _ = chain_rate(
            lambda zz, n_it: run(zz, n_it, reps=reps), z,
            n_short=iters // 10, n_long=iters,
        )
        return per

    # nominal op counts use the mask-op convention of the probe mixes
    # (pallas_kernels.vpu_probe_pallas): each reduction-feeding `where`
    # select counts one op/elt — dualdim's 22 includes its TWO row
    # masks exactly as dualdim_lean's 14 includes its one
    PROBES = {
        ("heat5", "float32"): (11, (64, 256, 1024)),
        ("heat5", "bfloat16"): (11, (64, 256, 1024)),
        ("dualdim", "float32"): (22, (32, 128, 512)),
        ("dualdim", "bfloat16"): (22, (32, 128, 512)),
        ("dualdim_lean", "float32"): (14, (32, 128, 512)),
        ("dualdim_lean", "bfloat16"): (14, (32, 128, 512)),
    }
    probe_rate = {}
    for (mix, dname), (ops, reps3) in PROBES.items():
        ts = np.array([probe_per_call(mix, r, dname) for r in reps3])
        rarr = np.array(reps3, np.float64)
        per_rep, _ = np.polyfit(rarr, ts, 1)
        mid_pred = ts[0] + (ts[2] - ts[0]) * (rarr[1] - rarr[0]) / (
            rarr[2] - rarr[0]
        )
        lin = ts[1] / mid_pred
        if not (0.85 <= lin <= 1.15):
            per_rep = float("nan")  # invalid must look invalid
        probe_rate[(mix, dname)] = elems / per_rep  # element-steps / s
        _emit(results, f"vpu_{mix}_{dname}_gops",
              elems * ops / per_rep / 1e9, "Gop/s",
              f"{H}x{W} {dname} resident; {per_rep / elems * 1e12:.2f} "
              f"ps/elt/rep; nominal {ops} ops/elt; reps={reps3}; "
              f"linearity {lin:.3f}")

    # heat marginal per-step cost vs its own-mix ceiling, f32 and bf16
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("x", "y"))
    n = 2048
    ks = (2, 4, 6, 8)
    for dtype in (np.float32, jnp.bfloat16):
        dname = jnp.dtype(dtype).name
        itemsize = jnp.dtype(dtype).itemsize
        t_call = {}
        for k in ks:
            z0h = np.random.default_rng(1).normal(
                size=(n + 2 * k, n + 2 * k)
            ).astype(dtype) / np.asarray(10, dtype)
            run = heat_step2d_fn(
                mesh, "x", "y", k, 0.05, 0.05, steps=k, kernel="pallas"
            )
            z = jnp.asarray(z0h)
            z = block(run(z, 1))
            z = block(run(z, 1))
            sec, z = chain_rate(
                run, z, n_short=max(2, 50 // k), n_long=max(20, 2000 // k)
            )
            t_call[k] = sec
            del z
        karr = np.array(ks, np.float64)
        tarr = np.array([t_call[k] for k in ks])
        b, a = np.polyfit(karr, tarr, 1)
        kernel_rate = n * n / b
        frac = kernel_rate / probe_rate[("heat5", dname)]
        bytes_call = 2 * (n + 2 * 4) ** 2 * itemsize  # in+out passes
        bytes_time = bytes_call / (STREAM_GBPS * 1e9)
        _emit(results, f"roofline_heat_{dname}_marginal_us", b * 1e6,
              "us/step",
              f"fit t(k)=a+b*k over k={ks} at {n}^2; a={a * 1e6:.0f} us "
              f"(launch + 2 HBM passes: bytes model {bytes_time * 1e6:.0f} "
              f"us)")
        _emit(results, f"roofline_heat_{dname}_vs_ops_ceiling", frac,
              "ratio",
              "marginal element rate / heat5 in-VMEM probe rate (ops "
              "axis; the marginal step is compute-side by construction "
              "— HBM lives in the intercept)")

    # dual-dim one-shot kernel: t(elems) = a + c*elems over 3 sizes,
    # chained via z + eps*residual (the +2 HBM passes are charged below).
    # Round-5 op diet: BOTH kernel bodies (raw 4-tap vs lean
    # difference-form, `lean=`) measured INTERLEAVED per size — the bf16
    # tier reads issue-bound (ops axis ~= bytes axis with imperfect
    # overlap), so saved vector ops should convert to wall-clock; the
    # A/B records whether they do.
    for dtype in (np.float32, jnp.bfloat16):
        dname = jnp.dtype(dtype).name
        itemsize = jnp.dtype(dtype).itemsize
        sizes = (2056, 2904, 4104)
        t_call: dict = {False: {}, True: {}}
        for nn in sizes:
            z0d = np.random.default_rng(2).normal(
                size=(nn, nn)
            ).astype(dtype) / np.asarray(10, dtype)
            eps = jnp.asarray(1e-6, dtype)

            @functools.partial(jax.jit, donate_argnums=0,
                               static_argnames=("lean",))
            def run(z, n_iter, lean, eps=eps):
                def body(_, zz):
                    # tile_rows pinned: the calibrated bf16 fit admits
                    # B=256 at the two smaller widths but caps 128 at
                    # 4104 — an unpinned sweep would blend two block
                    # schedules into one marginal fit
                    _, _, r = PK.dual_dim_step_pallas(zz, N_BND, 1.0, 1.0,
                                                      tile_rows=128,
                                                      lean=lean)
                    return zz + eps * r.astype(zz.dtype)

                return lax.fori_loop(
                    0, jnp.asarray(n_iter, jnp.int32), body, z
                )

            iters = max(40, 400 * 2056 ** 2 // nn ** 2)
            for lean in (False, True):
                z = jnp.asarray(z0d)
                z = block(run(z, 1, lean=lean))
                z = block(run(z, 1, lean=lean))
                # min-of-2 chained readings per size (chain_rate
                # repeats): contention only INFLATES, and a single
                # inflated point is exactly what NaN'd this fit's
                # linearity gate in 2 of 3 round-5 windows
                sec, z = chain_rate(
                    lambda zz, n_it, lean=lean: run(zz, n_it, lean=lean),
                    z, n_short=iters // 10, n_long=iters, repeats=2,
                )
                t_call[lean][nn] = sec
                del z
        earr = np.array([nn * nn for nn in sizes], np.float64)
        bytes_time = 5 * itemsize / (STREAM_GBPS * 1e9)
        cs = {}
        for lean in (False, True):
            tarr = np.array([t_call[lean][nn] for nn in sizes])
            c, a = np.polyfit(earr, tarr, 1)
            mid_pred = tarr[0] + (tarr[2] - tarr[0]) * (
                earr[1] - earr[0]
            ) / (earr[2] - earr[0])
            lin = tarr[1] / mid_pred
            fit_suspect = not (0.85 <= lin <= 1.15)
            # bytes per element: read z + write dx + dy (~3 arrays) +
            # res tiles (negligible) + the chain feedback's read+write
            mix = "dualdim_lean" if lean else "dualdim"
            ops_time = 1.0 / probe_rate[(mix, dname)]
            # a NaN probe rate (linearity-gated) must invalidate the
            # derived ceiling rows too — NaN comparisons are silently
            # False and would mislabel the bytes number as an
            # ops-ceiling fraction. It does NOT invalidate the raw/lean
            # gain row below: that ratio compares the two wall-clock
            # fits only, so it is gated on fit_suspect alone.
            suspect = fit_suspect or not np.isfinite(ops_time)
            binding = "bytes" if bytes_time > ops_time else "ops"
            model = max(bytes_time, ops_time)
            # physical-bound gate: a measured marginal BELOW the bytes
            # model is impossible regardless of which axis binds (5 HBM
            # passes cannot beat the marginal stream rate) — an
            # inflated small-size point flattens the slope without
            # tripping the linearity gate (one round-5 window read
            # 18.1 ps/elt = "1.49x the ceiling" with linearity 0.883).
            # 1.1 allows fit noise.
            impossible = (np.isfinite(c) and c > 0
                          and bytes_time / c > 1.1)
            fit_suspect = fit_suspect or impossible
            suspect = suspect or impossible
            cs[lean] = float("nan") if fit_suspect else c
            _emit(results, f"roofline_{mix}_{dname}_marginal_ps",
                  float("nan") if suspect else c * 1e12, "ps/elt",
                  f"fit t=a+c*elems over {sizes}; a={a * 1e6:.0f} us; "
                  f"linearity {lin:.3f}; ops axis {ops_time * 1e12:.2f} "
                  f"ps/elt, bytes axis (5 passes incl. chain feedback) "
                  f"{bytes_time * 1e12:.2f} ps/elt -> {binding}-bound"
                  + ("; SUB-PHYSICAL slope (below the bytes model): "
                     "inflated small-size point, fit invalid"
                     if impossible else ""))
            _emit(results, f"roofline_{mix}_{dname}_vs_ceiling",
                  float("nan") if suspect else model / c, "ratio",
                  f"binding-axis model time / measured marginal (1.0 = "
                  f"at the {binding} ceiling)")
        reads = " ".join(
            f"{nn}:[raw {t_call[False][nn] * 1e3:.2f}, lean "
            f"{t_call[True][nn] * 1e3:.2f}]ms" for nn in sizes
        )
        _emit(results, f"dualdim_lean_gain_{dname}",
              cs[False] / cs[True], "x",
              f"raw marginal / lean marginal, interleaved per size "
              f"(>1 = lean faster); per-size calls {reads}")

    # VERDICT r4 #5: heat bf16 block-size A/B above the launch floor —
    # tall 2048-wide domain, B=128 vs 256, interleaved twice, min per arm
    k = 4
    nx, ny = 16384 + 2 * k, 2048 + 2 * k
    z0t = np.random.default_rng(3).normal(
        size=(nx, ny)
    ).astype(jnp.bfloat16) / np.asarray(10, jnp.bfloat16)
    @functools.partial(jax.jit, donate_argnums=0, static_argnames=("B",))
    def run_tall(z, n_iter, B):
        def body(_, zz):
            return PK.heat2d_pallas(zz, 0.05, 0.05, steps=k,
                                    n_bnd=k, tile_rows=B)

        return lax.fori_loop(0, jnp.asarray(n_iter, jnp.int32), body, z)

    reads: dict[int, list] = {128: [], 256: []}
    for _ in range(2):
        for B in (128, 256):
            z = jnp.asarray(z0t)
            z = block(run_tall(z, 1, B=B))
            z = block(run_tall(z, 1, B=B))
            sec, z = chain_rate(
                lambda zz, n_it, B=B: run_tall(zz, n_it, B=B), z,
                n_short=5, n_long=105,
            )
            reads[B].append(sec)
            del z
    for B, rs in reads.items():
        per = min(rs)
        _emit(results, f"heat_bf16_tall_B{B}_steps_per_s", k / per,
              "steps/s",
              f"{nx}x{ny} bf16 k={k}, tile_rows={B}; reads "
              f"[{','.join(f'{r * 1e3:.2f}' for r in rs)}] ms/call "
              f"(call work ~16x the 2048^2 rows' — above the ~100 us "
              f"launch floor)")
    _emit(results, "heat_bf16_tall_B128_over_B256",
          min(reads[128]) / min(reads[256]), "x",
          "per-call time ratio, interleaved same-window; <1 = 128-row "
          "blocks faster")

    # VERDICT r4 #4 re-sweep: the round-5 dual-dim bf16 calibration
    # (temps 22 -> 10.4 B/elt) newly admits 256-row blocks at ≤~2.8k
    # widths — A/B the admitted width at a tall domain (above the launch
    # floor), interleaved twice, min per arm
    nxd, nyd = 16384 + 2 * N_BND, 2056
    z0d2 = np.random.default_rng(4).normal(
        size=(nxd, nyd)
    ).astype(jnp.bfloat16) / np.asarray(10, jnp.bfloat16)
    @functools.partial(jax.jit, donate_argnums=0, static_argnames=("B",))
    def rund(z, n_iter, B):
        def body(_, zz):
            _, _, r = PK.dual_dim_step_pallas(
                zz, N_BND, 1.0, 1.0, tile_rows=B
            )
            return zz + (
                jnp.asarray(1e-6, jnp.float32) * r.astype(jnp.float32)
            ).astype(zz.dtype)

        return lax.fori_loop(0, jnp.asarray(n_iter, jnp.int32), body, z)

    dreads: dict[int, list] = {128: [], 256: []}
    for _ in range(2):
        for B in (128, 256):
            z = jnp.asarray(z0d2)
            z = block(rund(z, 1, B=B))
            z = block(rund(z, 1, B=B))
            sec, z = chain_rate(
                lambda zz, n_it, B=B: rund(zz, n_it, B=B), z,
                n_short=10, n_long=210,
            )
            dreads[B].append(sec)
            del z
    for B, rs in dreads.items():
        _emit(results, f"dualdim_bf16_tall_B{B}_ms_per_call",
              min(rs) * 1e3, "ms",
              f"{nxd}x{nyd} bf16, tile_rows={B}; reads "
              f"[{','.join(f'{r * 1e3:.2f}' for r in rs)}]")
    _emit(results, "dualdim_bf16_tall_B128_over_B256",
          min(dreads[128]) / min(dreads[256]), "x",
          "per-call time ratio, interleaved same-window; <1 = 128-row "
          "blocks faster")


def _make_stripe_cell_measurer(w, lq, d, dtype="float32"):
    """Shared (rank, step)-cell timing machinery for the stripe groups:
    one compiled per-step flash executable per (k_tile, skip_tile) —
    offsets/stride are traced SMEM scalars, so every ring cell of a
    layout reuses it — timed with 3300-call chains and one contention
    retry. ``dtype`` applies to q/k/v (the online-softmax carry stays
    f32 as in the kernel contract). Returns
    ``measured(qo, ko, st, kt, skt) -> sec``."""
    import functools

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.instrument.timers import block, chain_rate
    from tpu_mpi_tests.kernels import pallas_kernels as PK

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(lq, d)).astype(np.float32), dtype)
    kb = jnp.asarray(rng.normal(size=(lq, d)).astype(np.float32), dtype)
    vb = jnp.asarray(rng.normal(size=(lq, d)).astype(np.float32), dtype)
    scale = 1.0 / d**0.5

    # sub-f32 cells run DEFAULT matmul precision, matching every
    # historical BASELINE bf16 attention row (HIGHEST's upcast path is
    # the documented numeric default but not the benchmarked config)
    prec = (jax.lax.Precision.HIGHEST if jnp.dtype(dtype).itemsize >= 4
            else jax.lax.Precision.DEFAULT)

    @functools.partial(
        jax.jit, donate_argnums=(0,), static_argnames=("kt", "skt")
    )
    def fold(carry, qq, kk, vv, qo, ko, st, n_iter, kt, skt):
        def body(_, c):
            m, l, acc = c
            return PK.flash_attention_block_pallas(
                qq, kk, vv, m, l, acc, qo, ko, scale=scale, causal=True,
                pos_stride=st, k_tile=kt, skip_tile=skt, precision=prec,
            )

        return lax.fori_loop(0, jnp.asarray(n_iter, jnp.int32), body, carry)

    def cell_time(qo, ko, st, kt, skt):
        m0 = jnp.full((lq, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((lq, 1), jnp.float32)
        acc0 = jnp.zeros((lq, d), jnp.float32)
        offs = (jnp.int32(qo), jnp.int32(ko), jnp.int32(st))
        state = block(fold((m0, l0, acc0), q, kb, vb, *offs, 1,
                           kt=kt, skt=skt))
        sec, state = chain_rate(
            lambda c, n: fold(c, q, kb, vb, *offs, n, kt=kt, skt=skt),
            state, n_short=300, n_long=3300,
        )
        del state
        return sec

    def measured(qo, ko, st, kt, skt):
        sec = cell_time(qo, ko, st, kt, skt)
        if not np.isfinite(sec):
            sec = cell_time(qo, ko, st, kt, skt)  # one contention retry
        # a NaN on a live cell stays NaN: it poisons the sums so an
        # invalid grid cannot masquerade as a measured speedup
        return sec

    return measured


def _paced_with_suspect(t):
    """Shared grid-validity companion to the stripe cell measurer:
    paced proxy Σ_s max_r plus the checks both stripe groups need — a
    non-finite cell (double chain failure; NaN poisons the sums by
    design) or a lone live cell >5× the grid median (contention spike
    that the NaN retry cannot see) marks the grid suspect, with a
    human-readable note. Returns ``(paced_sec, note, suspect)``."""
    import numpy as np

    note = ""
    suspect = False
    if not np.all(np.isfinite(t)):
        suspect = True
        note = "; NaN cell(s) after retry: grid invalid"
    else:
        live = t[t > 0]
        med = np.median(live) if live.size else 0.0
        if live.size and live.max() > 5 * med:
            suspect = True
            note = (f"; OUTLIER-SUSPECT: max cell "
                    f"{live.max() * 1e3:.2f} ms vs median "
                    f"{med * 1e3:.3f}")
    return t.max(axis=0).sum(), note, suspect


def _best_finite_arm(paced):
    """NaN-safe best-arm pick for a {arm: seconds} dict: min over
    finite values only — a plain ``min(paced, key=paced.get)`` can
    return a NaN arm (NaN comparisons are always False), reporting an
    unmeasured grid as the sweep winner. Returns None when no arm is
    finite."""
    import numpy as np

    finite = {s: p for s, p in paced.items() if np.isfinite(p)}
    return min(finite, key=finite.get) if finite else None


def bench_stripeskip(results):
    """Round-5 follow-up sweep: the striped ring's ``skip_tile`` (the
    masked band sub-span width) was SET to 256 when the skip/rescale
    decoupling shipped — 256 ≈ the band width per 4096-row block at
    w=8 — but never swept. Narrower spans waste less band-edge rounding
    (≤ skip_tile/2 columns) at more per-span carry updates; wider the
    reverse. Sweep ``TPU_MPI_STRIPE_SKIPS`` (default 128,256,512) at
    the production k_tile on the striped grid only (contig's measured
    default is the coupled loop), every skip's cell measured
    INTERLEAVED per (rank, step) so all arms share contention windows;
    paced proxy Σ_s max_r compared across skips. A winner that
    separates from the ±3-5%% band in REPLICATED windows justifies
    changing ``MEASURED_BEST_SKIP_TILE['striped']``; otherwise 256
    stands confirmed."""
    import numpy as np

    w, lq, d = 8, 4096, 128
    measured = _make_stripe_cell_measurer(w, lq, d)
    kt = int(os.environ.get("TPU_MPI_STRIPE_SKIP_KT", "2048"))
    # dedup (order-preserving): a duplicated value in the env list would
    # silently re-measure 64 cells per duplicate and emit its row twice
    skips = tuple(dict.fromkeys(
        int(x) for x in os.environ.get(
            "TPU_MPI_STRIPE_SKIPS", "128,256,512"
        ).split(",")
    ))
    grids = {skt: np.zeros((w, w)) for skt in skips}
    for r in range(w):
        for s in range(w):
            src = (r - s) % w
            for skt in skips:
                grids[skt][r, s] = measured(r, src, w, kt, skt)
    suspect = False
    paced = {}
    for skt, t in grids.items():
        paced[skt], note, gsusp = _paced_with_suspect(t)
        suspect = suspect or gsusp
        _emit(results, f"stripeskip_skip{skt}_kt{kt}_paced_ms",
              paced[skt] * 1e3, "ms",
              f"striped decoupled paced proxy, w={w} lq={lq} d={d}; "
              f"total work {t.sum() * 1e3:.2f} ms{note}")
    best = _best_finite_arm(paced)
    _emit(results, f"stripeskip_best_kt{kt}",
          float("nan") if (suspect or best is None) else float(best),
          "skip_tile",
          (f"fastest paced arm of {skips}; margins vs best: "
           + " ".join(f"{s}:{paced[s] / paced[best]:.3f}x"
                      for s in skips) if best is not None
           else f"no finite arm of {skips}")
          + ("; NaN: a suspect grid invalidates the pick"
             if suspect else ""))


def bench_stripebalance(results):
    """Striped causal ring balance, measured on ONE chip (round 4,
    VERDICT r3 next #4). The ring's wall-clock is paced per step by its
    slowest rank, so the single-chip proxy is: time the per-step flash
    kernel at EVERY (rank, step) cell of a w=8 ring — contiguous vs
    striped layout — and compare Σ_s max_r t(r,s) (the paced proxy) and
    Σ_{r,s} t(r,s) (total work). One compiled executable serves all
    cells (offsets/stride are traced SMEM scalars driving the causal
    tile-skip), so cells differ only by the masking geometry. Also
    measures the to_striped/from_striped conversion cost at the same
    (L, d).

    Expected shape of the result: contiguous keeps SOME rank full-live
    at every step (rank w−1 is live at all of them), so Σ_s max_r ≈
    w × full-block cost; striped makes every cell ~half-live, so the
    paced proxy halves while total work stays ~equal."""
    import functools

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_mpi_tests.comm.ring import from_striped, to_striped
    from tpu_mpi_tests.instrument.timers import block, chain_rate

    w, lq, d = 8, 4096, 128
    # dtype axis (round-5 end): the balance/decoupling verdicts were
    # f32-evidenced while production attention mostly runs bf16 — and
    # dtype has inverted a scheduling preference in this repo before
    # (the dtype-dim inversion). TPU_MPI_STRIPE_DTYPE=bfloat16 re-runs
    # the grids at 16-bit (DEFAULT matmul precision, the benchmarked
    # bf16 config); rows gain a _bfloat16 tag so f32 history stays
    # comparable.
    sdtype = os.environ.get("TPU_MPI_STRIPE_DTYPE", "float32")
    dtag = "" if sdtype == "float32" else f"_{sdtype}"
    measured = _make_stripe_cell_measurer(w, lq, d, dtype=sdtype)

    # k_tile axis: the striped layout's ~2x balance is realized only at
    # fine skip granularity — at k_tile=2048 a 4096-row block has 2 k
    # tiles, and every ~half-live striped cell rounds UP to ~75% of full
    # work (the masked halves of live tiles still run their matmuls),
    # while finer tiles skip more but pay more per-tile carry rescale.
    # The two layouts' cells are measured INTERLEAVED per (r, s): the
    # (suspect flag propagates to the derived speedup row — that is the
    # metric an outlier actually invalidates)
    # shared chip's contention windows drift minute-to-minute, and a
    # layout-per-pass structure let one layout land in a slow window
    # (first cut measured the contig cells 2x apart across two runs
    # while striped held still, moving the headline ratio 2.4x -> 1.25x)
    kts = tuple(
        int(x) for x in os.environ.get(
            "TPU_MPI_STRIPE_KTS", "2048,512"
        ).split(",")
    )
    # per-layout skip axis (round 5): contig cells at the MEASURED-best
    # coupled path (skip=0 — the homogeneous masked loop pipelines best
    # on the narrow diagonal band), striped cells at BOTH skip modes so
    # the decoupling's striped win is same-window evidenced
    for kt in kts:
        grids = {"contig": np.zeros((w, w)), "striped": np.zeros((w, w)),
                 "striped_coupled": np.zeros((w, w))}
        skipped = 0
        suspect = False
        for r in range(w):
            for s in range(w):
                src = (r - s) % w
                if src > r:
                    # contig cell geometrically dead (whole K block in
                    # the future, every k tile skips): 0 unmeasured —
                    # its true cost is the shared per-call overhead,
                    # cancelled by the differencing everywhere else
                    skipped += 1
                else:
                    grids["contig"][r, s] = measured(
                        r * lq, src * lq, 1, kt, 0
                    )
                grids["striped"][r, s] = measured(r, src, w, kt, 256)
                grids["striped_coupled"][r, s] = measured(r, src, w, kt, 0)
        for name, t in grids.items():
            note = (f"; {skipped} geometrically-dead cells set to 0 "
                    f"unmeasured" if name == "contig" else "")
            # a contention spike can inflate one cell 10-30x without
            # tripping the NaN retry; _paced_with_suspect makes such
            # grids self-identifying (a 9.4 ms striped paced reading in
            # one replicate traced to exactly this)
            paced_sec, gnote, gsusp = _paced_with_suspect(t)
            suspect = suspect or gsusp
            note += gnote
            _emit(results, f"stripe_{name}_kt{kt}{dtag}_paced_ms",
                  paced_sec * 1e3, "ms",
                  f"sum over steps of max-rank per-step flash time, "
                  f"w={w} lq={lq} d={d}; total work "
                  f"{t.sum() * 1e3:.2f} ms; last-rank sum "
                  f"{t[w - 1].sum() * 1e3:.2f} ms{note}")
        speedup = (grids["contig"].max(axis=0).sum()
                   / grids["striped"].max(axis=0).sum())
        work_ratio = grids["striped"].sum() / grids["contig"].sum()
        _emit(results, f"stripe_paced_speedup_kt{kt}{dtag}",
              float("nan") if suspect else speedup, "x",
              f"contig/striped paced proxy, cells interleaved "
              f"same-window; total-work ratio {work_ratio:.3f} "
              f"(~1 = balance moved work, not added it)"
              + ("; NaN: a suspect grid (outlier or NaN cell — see "
                 "grid rows) invalidates the derived speedup"
                 if suspect else ""))
        skip_gain = (grids["striped_coupled"].max(axis=0).sum()
                     / grids["striped"].max(axis=0).sum())
        _emit(results, f"stripe_skip_decouple_gain_kt{kt}{dtag}",
              float("nan") if suspect else skip_gain, "x",
              f"striped coupled(skip=0)/decoupled(skip=256) paced "
              f"proxy, same cells interleaved; work ratio "
              f"{grids['striped'].sum() / grids['striped_coupled'].sum():.3f}")

    # layout conversion cost at the same global (L, d) — what a caller
    # pays once before/after the whole ring pass, not per step; measured
    # at the sweep's dtype (a bf16 run must not silently re-measure the
    # f32 conversion and double-record against f32 history)
    L = w * lq
    rng = np.random.default_rng(0)
    xg = jnp.asarray(rng.normal(size=(L, d)), dtype=sdtype)
    for nm, fn in (("to_striped", to_striped), ("from_striped",
                                               from_striped)):
        @functools.partial(jax.jit, donate_argnums=0)
        def run(x, n_iter, fn=fn):
            return lax.fori_loop(
                0, jnp.asarray(n_iter, jnp.int32),
                lambda _, c: fn(c, world=w), x
            )

        x = jnp.array(xg, copy=True)  # run donates x; xg must survive
        # warm the MEASURED chained executable (not the raw fn): the
        # tunnel charges a one-time ~0.9 s cost to an executable's
        # second dispatch (bench_heat note) — warming something else
        # lets that land inside n_short and flip the delta negative
        x = block(run(x, 1))
        x = block(run(x, 1))
        sec, x = chain_rate(run, x, n_short=50, n_long=550)
        _emit(results, f"stripe_{nm}{dtag}_ms", sec * 1e3, "ms",
              f"({L}, {d}) {sdtype} permute, one-off per ring pass")
        del x


GROUPS = {
    "daxpy": bench_daxpy,
    "stencil": bench_stencil,
    "iterate": bench_iterate,
    "splitfused": bench_splitfused,
    "ceiling": bench_ceiling,
    "attention": bench_attention,
    "heat": bench_heat,
    "blocks": bench_blocks,
    "causal": bench_causal,
    "streams": bench_streams,
    "vpu": bench_vpu,
    "stripebalance": bench_stripebalance,
    "stripeskip": bench_stripeskip,
    "roofline2": bench_roofline2,
}


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:]) or list(GROUPS)
    unknown = [a for a in args if a not in GROUPS]
    if unknown:
        print(f"unknown groups {unknown}; valid: {list(GROUPS)}",
              file=sys.stderr)
        return 2
    results = []
    for g in args:
        GROUPS[g](results)
    width = max(len(r["metric"]) for r in results) if results else 0
    print("-" * (width + 20))
    for r in results:
        print(f"{r['metric']:<{width}}  {r['value']:>10} {r['unit']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
