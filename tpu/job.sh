#!/bin/bash
# Batch sweep-matrix submission (≅ summit/job.lsf:9-16 wrapped around
# summit/run.sh, and jlse/job.pbs:14-21): enumerate {world sizes ×
# drivers × memory spaces × profilers}, run every cell through run.sh,
# and finish with an avg.py summary over the collected out-*.txt — ONE
# command reproduces the reference's whole result matrix.
#
# Usage: ./job.sh [-w "1 2"] [-d "mpi_daxpy_nvtx"] [-s "device managed"]
#                 [-p "none xprof"] [-a PATTERN] [-- driver args...]
#   -w  world sizes (space-separated). 1 runs on the active backend (one
#       real chip, or the CPU fake-device mesh the driver args select);
#       N>1 spawns N localhost processes with 1 fake CPU device each in a
#       real jax.distributed world (the dev-loop stand-in for a pod —
#       on an actual multi-host pod, run run.sh per worker instead).
#   -d  driver modules under tpu_mpi_tests.drivers
#   -s  memory-space twins (≅ um|noum managed/unmanaged binaries)
#   -p  profiler modes (xprof traces land under profile/<tag>, named
#       per rank — the %q{PMIX_RANK} analog)
#   -a  avg.py pattern for the final summary (default: gather, the
#       reference's avg.sh default)
# Extra args after -- go to every driver cell verbatim.
#
# Output: out-<space>_<prof>_<driver>_<host>[_rN].txt per cell (rank) in
# the CWD, then the aggregated table on stdout.

set -eu

worlds="1"
drivers="mpi_daxpy_nvtx"
spaces="device"
profs="none"
avg_pattern="gather"

while getopts "w:d:s:p:a:h" opt; do
  case "$opt" in
    w) worlds=$OPTARG ;;
    d) drivers=$OPTARG ;;
    s) spaces=$OPTARG ;;
    p) profs=$OPTARG ;;
    a) avg_pattern=$OPTARG ;;
    h)
      # header block only (lines 2..first blank): skips the shebang and
      # any later in-body comments
      sed -n '2,/^$/p' "$0" | grep '^#' | sed 's/^# \{0,1\}//'
      exit 0
      ;;
    *) exit 1 ;;
  esac
done
shift $((OPTIND - 1))

tpu_dir=$(cd "$(dirname "$0")" && pwd)
run_sh=$tpu_dir/run.sh
. "$tpu_dir/worldlib.sh"

for w in $worlds; do
  for driver in $drivers; do
    for space in $spaces; do
      for prof in $profs; do
        echo "== cell: world=${w} driver=${driver} space=${space}" \
          "prof=${prof}" >&2
        if [ "$w" -eq 1 ]; then
          "$run_sh" "$space" "$prof" "$driver" "$@"
        else
          # run.sh names each rank's own out-<tag>.txt (world+rank in
          # the tag), so no -o redirection here
          if ! spawn_world "$w" "$run_sh" "$space" "$prof" "$driver" \
            --fake-devices 1 "$@"; then
            echo "cell failed" >&2
            exit 1
          fi
        fi
      done
    done
  done
done

echo "== matrix complete; aggregating (pattern=${avg_pattern}) =="
python "$(dirname "$run_sh")/avg.py" --pattern "$avg_pattern" out-*.txt
