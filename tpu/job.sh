#!/bin/bash
# Batch sweep-matrix submission (≅ summit/job.lsf:9-16 wrapped around
# summit/run.sh, and jlse/job.pbs:14-21): enumerate {world sizes ×
# drivers × memory spaces × profilers}, run every cell through run.sh,
# and finish with an avg.py summary over the collected out-*.txt — ONE
# command reproduces the reference's whole result matrix.
#
# Usage: ./job.sh [-w "1 2"] [-d "mpi_daxpy_nvtx"] [-s "device managed"]
#                 [-p "none xprof"] [-a PATTERN]
#                 [-x "driver=args ..."] [-- driver args...]
#   -w  world sizes (space-separated). 1 runs on the active backend (one
#       real chip, or the CPU fake-device mesh the driver args select);
#       N>1 spawns N localhost processes with 1 fake CPU device each in a
#       real jax.distributed world (the dev-loop stand-in for a pod —
#       on an actual multi-host pod, run run.sh per worker instead).
#   -d  driver modules under tpu_mpi_tests.drivers
#   -s  memory-space twins (≅ um|noum managed/unmanaged binaries)
#   -p  profiler modes (xprof traces land under profile/<tag>, named
#       per rank — the %q{PMIX_RANK} analog)
#   -a  avg.py pattern for the final summary (default: gather, the
#       reference's avg.sh default)
#   -x  per-driver extra args, "driver=args..." (repeatable; repeats
#       for one driver append) — the analog of job.lsf's per-binary
#       invocation lines; e.g. -x "stencil2d=--n-iter 30" sizes one
#       driver's cells without touching the others. Args are split on
#       whitespace with no quote parsing: values containing spaces
#       cannot be passed through -x
# Extra args after -- go to every driver cell verbatim (all drivers
# must accept them).
#
# Output: out-<space>_<prof>_<driver>_<host>[_rN].txt per cell (rank) in
# the CWD, then the aggregated table on stdout.

set -eu

worlds="1"
drivers="mpi_daxpy_nvtx"
spaces="device"
profs="none"
avg_pattern="gather"
declare -A driver_extra=()

while getopts "w:d:s:p:a:x:h" opt; do
  case "$opt" in
    w) worlds=$OPTARG ;;
    d) drivers=$OPTARG ;;
    s) spaces=$OPTARG ;;
    p) profs=$OPTARG ;;
    a) avg_pattern=$OPTARG ;;
    x)
      key=${OPTARG%%=*}
      if [ "$key" == "$OPTARG" ] || [ -z "$key" ]; then
        echo "-x needs driver=args, got: $OPTARG" >&2
        exit 1
      fi
      # repeats for the same driver APPEND (the help text advertises
      # -x as repeatable; silent overwrite would drop earlier sizing)
      driver_extra[$key]="${driver_extra[$key]:-} ${OPTARG#*=}"
      ;;
    h)
      # header block only (lines 2..first blank): skips the shebang and
      # any later in-body comments
      sed -n '2,/^$/p' "$0" | grep '^#' | sed 's/^# \{0,1\}//'
      exit 0
      ;;
    *) exit 1 ;;
  esac
done
shift $((OPTIND - 1))

tpu_dir=$(cd "$(dirname "$0")" && pwd)
run_sh=$tpu_dir/run.sh
. "$tpu_dir/worldlib.sh"

# -x keys must name drivers that will actually run, or a typo silently
# produces a default-sized sweep read as if the extras applied
for key in "${!driver_extra[@]}"; do
  case " $drivers " in
    *" $key "*) ;;
    *)
      echo "-x driver '$key' not in -d list ($drivers)" >&2
      exit 1
      ;;
  esac
done

for w in $worlds; do
  for driver in $drivers; do
    for space in $spaces; do
      for prof in $profs; do
        echo "== cell: world=${w} driver=${driver} space=${space}" \
          "prof=${prof}" >&2
        # split the per-driver extras into words WITHOUT pathname
        # expansion (read -ra does not glob; a bare $var would expand
        # patterns against the out-*.txt files this very script writes)
        read -ra cell_extra <<< "${driver_extra[$driver]:-}"
        if [ "$w" -eq 1 ]; then
          "$run_sh" "$space" "$prof" "$driver" \
            ${cell_extra[@]+"${cell_extra[@]}"} "$@"
        else
          # run.sh names each rank's own out-<tag>.txt (world+rank in
          # the tag), so no -o redirection here
          if ! spawn_world "$w" "$run_sh" "$space" "$prof" "$driver" \
            --fake-devices 1 ${cell_extra[@]+"${cell_extra[@]}"} "$@"; then
            echo "cell failed" >&2
            exit 1
          fi
        fi
      done
    done
  done
done

echo "== matrix complete; aggregating (pattern=${avg_pattern}) =="
python "$(dirname "$run_sh")/avg.py" --pattern "$avg_pattern" out-*.txt
