# Shared localhost-world launcher (sourced by run_local_multiproc.sh and
# job.sh): spawn N copies of a command wired into one real
# jax.distributed world over localhost (≅ `mpirun -np N`, jlse/run.sh).
#
#   spawn_world [-o OUT_PREFIX] NPROCS COMMAND [ARGS...]
#
# Each rank gets JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
# JAX_PROCESS_ID; with -o, rank i's stdout+stderr land in
# <OUT_PREFIX><i>.txt (parallel children interleave a shared pipe).
# Returns the first nonzero child exit code.

spawn_world() {
  local out_prefix=""
  if [ "${1:-}" == "-o" ]; then
    out_prefix=$2
    shift 2
  fi
  local nprocs=$1
  shift
  local port=$((10000 + RANDOM % 20000))
  local pids=() rc=0 st i pid
  for ((i = 0; i < nprocs; i++)); do
    if [ -n "$out_prefix" ]; then
      JAX_COORDINATOR_ADDRESS="localhost:${port}" \
      JAX_NUM_PROCESSES="$nprocs" \
      JAX_PROCESS_ID="$i" \
        "$@" > "${out_prefix}${i}.txt" 2>&1 &
    else
      JAX_COORDINATOR_ADDRESS="localhost:${port}" \
      JAX_NUM_PROCESSES="$nprocs" \
      JAX_PROCESS_ID="$i" \
        "$@" &
    fi
    pids+=($!)
  done
  for pid in "${pids[@]}"; do
    # keep the FIRST nonzero exit code (the documented contract); without
    # the guard a later failing child would overwrite it
    wait "$pid" || { st=$?; [ "$rc" -ne 0 ] || rc=$st; }
  done
  return "$rc"
}
