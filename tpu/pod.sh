#!/bin/bash
# Pod-day protocol (round 5, VERDICT r4 #7): ONE submission that converts
# multi-chip access into every hardware-blocked BASELINE row. The two
# gaps this environment cannot measure (BASELINE.md "What's missing") are
# multi-chip ICI wall-clock and real multi-host bootstrap; pointed at a
# real slice, this script produces:
#
#   1. the sharded resident-block bench.py headline at world>1
#      (both dtypes in one JSON line — BENCH_pod.json);
#   2. collbench ring sweeps over ICI: XLA collectives vs the hand RDMA
#      ring twins, allreduce_rdma at credits=1 AND credits=2 (the
#      double-buffered pod-latency experiment), ppermute = the halo
#      pattern's wire rate;
#   3. striped-vs-contiguous causal ring attention wall-clock at the
#      measured-best per-layout defaults (attnbench ring tier) — at
#      BOTH dtypes: the single-chip proxy says stripe pays at f32
#      (1.42-1.51x) and loses at bf16 (0.79-0.83x, BASELINE round-5
#      dtype note); the pod wall-clock with real ppermute overlap is
#      the open question for each;
#   4. the stencil2d halo-exchange driver at reference scale (the
#      job.sh matrix's communication-bound cell, exact-parity gated);
#   5. gather_inplace over the RDMA all-gather (donated-buffer parity).
#
# Every cell lands in OUTDIR as out-pod-<cell>.{txt,jsonl}; the run ends
# with PODRUN.json — a MULTICHIP_r{N}.json-shaped artifact:
#   {"ok": bool, "world": N, "platform": ..., "cells": {name: rc}, ...}
#
# Usage:
#   ./pod.sh                 # on the slice this host sees (jax.devices())
#   ./pod.sh -w 2 -c         # CI dry-run: 2-process localhost CPU world,
#                            # tiny shapes (the gate tests/test_pod.py
#                            # runs — zero new engineering on pod day)
#   ./pod.sh -o DIR          # write outputs under DIR (default .)
#
# Multi-host pods: run this per worker (gcloud ... --worker=all); the
# drivers bootstrap jax.distributed from the TPU VM metadata exactly as
# tpu/run.sh documents. The -w N localhost mode is the dev stand-in.

set -eu

outdir=.
world=0   # 0 = the devices this process sees (real slice)
ci=0
while getopts "o:w:ch" opt; do
  case "$opt" in
    o) outdir=$OPTARG ;;
    w) world=$OPTARG ;;
    c) ci=1 ;;
    h)
      sed -n '2,/^$/p' "$0" | grep '^#' | sed 's/^# \{0,1\}//'
      exit 0
      ;;
    *) exit 1 ;;
  esac
done
shift $((OPTIND - 1))

tpu_dir=$(cd "$(dirname "$0")" && pwd)
repo_dir=$(cd "$tpu_dir/.." && pwd)
. "$tpu_dir/worldlib.sh"
mkdir -p "$outdir"
cd "$outdir"
export PYTHONPATH="$repo_dir${PYTHONPATH:+:$PYTHONPATH}"

# cell sizing: CI dry-run uses tiny shapes so the 2-process CPU world
# finishes in seconds while still executing every code path (real
# collectives, RDMA interpret twins, striped ring, halo parity)
if [ "$ci" -eq 1 ]; then
  sizes_kib="4,64"
  coll_iter=20
  attn_args=(--seq-len 256 --head-dim 16 --n-iter 20)
  sten_args=(--n-local 32 --n-other 64 --n-iter 3)
  gather_args=(--n-per-rank 1024)
  bench_env=(TPU_MPI_BENCH_N=128 TPU_MPI_BENCH_ITERS_SHORT=50
             TPU_MPI_BENCH_ITERS_LONG=1050 TPU_MPI_BENCH_SAMPLES=1)
else
  sizes_kib="4,64,1024,16384"
  coll_iter=500
  attn_args=(--seq-len 32768 --head-dim 128 --n-iter 200)
  sten_args=(--n-local 2048 --n-other 4096 --n-iter 30)
  gather_args=(--n-per-rank 1048576)
  bench_env=()
fi
# dtype pairs for the attention cells (cell 3): bf16 runs the
# benchmarked 16-bit config (DEFAULT precision via --fast)
attn_f32=(--dtype float32)
attn_bf16=(--dtype bfloat16 --fast)

declare -A cell_rc=()
run_cell() {
  # run_cell NAME -- CMD...: capture stdout+stderr, record rc, keep going
  local name=$1
  shift 2
  echo "== pod cell: $name" >&2
  local rc=0
  if [ "$world" -gt 1 ]; then
    spawn_world -o "out-pod-${name}-r" "$world" \
      env JAX_PLATFORMS='' "$@" || rc=$?
  else
    "$@" > "out-pod-${name}.txt" 2>&1 || rc=$?
  fi
  cell_rc[$name]=$rc
  [ "$rc" -eq 0 ] || echo "   cell $name FAILED rc=$rc" >&2
}

# world>1 localhost mode: each process sees 1 fake CPU device; a real
# slice ("-w 0"/unset) lets every driver use all local devices
fake=()
if [ "$world" -gt 1 ]; then
  fake=(--fake-devices 1)
fi

# 1. the headline at world>1 (dual-dtype JSON line -> BENCH_pod.json)
if [ "$world" -gt 1 ]; then
  run_cell bench -- env ${bench_env[@]+"${bench_env[@]}"} \
    TPU_MPI_BENCH_FAKE_DEVICES=1 python "$repo_dir/bench.py"
else
  run_cell bench -- env "${bench_env[@]+"${bench_env[@]}"}" \
    python "$repo_dir/bench.py"
fi

# 2. collective ring sweeps: XLA tier + RDMA twins, credits 1 and 2
run_cell coll-xla -- python -m tpu_mpi_tests.drivers.collbench \
  "${fake[@]+"${fake[@]}"}" --sizes-kib "$sizes_kib" --n-iter "$coll_iter" \
  --jsonl out-pod-coll-xla.jsonl
run_cell coll-rdma-c1 -- python -m tpu_mpi_tests.drivers.collbench \
  "${fake[@]+"${fake[@]}"}" --sizes-kib "$sizes_kib" --n-iter "$coll_iter" \
  --collectives allgather_rdma,allreduce_rdma --rdma-credits 1 \
  --jsonl out-pod-coll-rdma-c1.jsonl
run_cell coll-rdma-c2 -- python -m tpu_mpi_tests.drivers.collbench \
  "${fake[@]+"${fake[@]}"}" --sizes-kib "$sizes_kib" --n-iter "$coll_iter" \
  --collectives allreduce_rdma --rdma-credits 2 \
  --jsonl out-pod-coll-rdma-c2.jsonl

# 3. causal ring attention: contiguous vs striped at BOTH dtypes,
#    per-layout measured-best defaults (BASELINE stripebalance's
#    multi-chip unknown is exactly this wall-clock overlap with
#    ppermute transfer; the layout verdict is dtype-dependent on the
#    single-chip proxy, so pod day measures each dtype's pair)
for dt in f32 bf16; do
  if [ "$dt" = f32 ]; then dt_args=("${attn_f32[@]}")
  else dt_args=("${attn_bf16[@]}"); fi
  run_cell "attn-contig-$dt" -- python -m tpu_mpi_tests.drivers.attnbench \
    "${fake[@]+"${fake[@]}"}" --tiers ring --causal \
    "${attn_args[@]}" "${dt_args[@]}" --jsonl "out-pod-attn-contig-$dt.jsonl"
  run_cell "attn-striped-$dt" -- python -m tpu_mpi_tests.drivers.attnbench \
    "${fake[@]+"${fake[@]}"}" --tiers ring --causal --stripe \
    "${attn_args[@]}" "${dt_args[@]}" --jsonl "out-pod-attn-striped-$dt.jsonl"
done

# 4. halo exchange at reference scale (exact-parity gated inside)
run_cell stencil2d -- python -m tpu_mpi_tests.drivers.stencil2d \
  "${fake[@]+"${fake[@]}"}" "${sten_args[@]}" \
  --jsonl out-pod-stencil2d.jsonl

# 5. in-place RDMA all-gather parity
run_cell gather-rdma -- python -m tpu_mpi_tests.drivers.gather_inplace \
  "${fake[@]+"${fake[@]}"}" "${gather_args[@]}" --rdma \
  --jsonl out-pod-gather.jsonl

# PODRUN.json: the MULTICHIP-shaped artifact
python - "$world" <<'EOF' "${!cell_rc[@]}" -- "${cell_rc[@]}"
import json
import sys

args = sys.argv[1:]
world = int(args[0])
sep = args.index("--")
names, rcs = args[1:sep], [int(r) for r in args[sep + 1:]]
cells = dict(zip(names, rcs))
try:
    import jax

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
except Exception as e:  # noqa: BLE001 — record, don't crash the artifact
    platform, n_dev = f"unavailable: {e}", 0
out = {
    "ok": all(r == 0 for r in cells.values()) and bool(cells),
    "world": world or 1,
    "devices_per_process": n_dev,
    "platform": platform,
    "cells": cells,
}
with open("PODRUN.json", "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
EOF

rc_total=0
for name in "${!cell_rc[@]}"; do
  [ "${cell_rc[$name]}" -eq 0 ] || rc_total=1
done
exit "$rc_total"
