"""Induced-drift serving demo: the closed tuning loop in one run.

Replaces the daxpy serve handler with a synthetic one whose service
time is keyed on the RESOLVED ``daxpy/chunk`` schedule: the pre-seeded
winner (chunk=1, warmed into ``--tune-cache`` before launch) silently
degrades after ``--drift-after`` batches — the "conditions drifted
under a tuned schedule" scenario fleet tuning exists for — while every
other candidate stays fast. Everything downstream is the REAL stack:
the metrics tee latches ``tune_stale`` when the class's achieved GB/s
sags below the winner's own baseline, and with ``--retune`` the serve
loop's controller re-sweeps between windows, hot-swaps the handler, and
the SLO windows recover; without it the run limps to the end and
``tpumt-doctor`` convicts ``stale_schedule``.

Used by ``make fleet-smoke`` (both leg shapes) and runnable by hand::

    python -m tpu.retune_demo [--drift-after=N] <tpumt-serve args...>

Every argument after the optional ``--drift-after=N`` is passed to
``tpumt-serve`` verbatim.
"""

from __future__ import annotations

import sys
import time


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    drift_after = 40
    if argv and argv[0].startswith("--drift-after="):
        drift_after = int(argv.pop(0).split("=", 1)[1])
    slow_s = 0.03   # the drifted winner's per-batch service time
    fast_s = 0.001  # every healthy candidate

    from tpu_mpi_tests.drivers import _common
    from tpu_mpi_tests.tune.sweep import ensure_tuned

    calls = {"n": 0}

    def drifting_daxpy_factory(mesh, shape, dtype):
        """The registry contract, synthetically timed: step(k) blocks
        (sleeps) for a duration keyed on the resolved chunk schedule,
        and carries the tune_info recipe the --retune controller
        rebuilds through."""

        def build(value=None):
            # explicit > cached > prior, through the real resolver: the
            # cached hit is what arms the metrics plane's stale watch
            # (a tune_hit record flows through the tee)
            eff = int(ensure_tuned(
                "daxpy/chunk", lambda c: 0.0, explicit=value,
            ))

            def step(k: int):
                calls["n"] += 1
                drifted = eff == 1 and calls["n"] > drift_after
                time.sleep(slow_s if drifted else fast_s)

            step.tune_info = {
                "knob": "daxpy/chunk",
                "ctx": {},
                "candidates": (1, 8, 32),
                "rebuild": build,
            }
            return step

        return build()

    # registered FIRST: register_workload is setdefault, so the spec's
    # own factory never displaces the drifting twin in this process
    _common.register_workload("daxpy", drifting_daxpy_factory)
    from tpu_mpi_tests.drivers import serve

    return serve.main(argv)


if __name__ == "__main__":
    sys.exit(main())
