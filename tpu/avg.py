#!/usr/bin/env python
"""Result aggregator (≅ avg.sh, /root/reference/avg.sh:1-15).

Prefers the native C++ aggregator (native/tpumt_avg, built on demand);
falls back to an equivalent Python implementation. Contract preserved from
the reference: select lines matching a pattern (default "gather"), average
the ':'-delimited second field per file. Extensions: ``--key`` extracts a
numeric field from JSONL records instead; ``--stats`` adds min/max/count.

Usage: avg.py [--pattern PAT] [--key JSONKEY] [--stats] [files...]
(default files: out-*.txt like the reference)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
NATIVE_DIR = REPO / "native"

# standalone script: make the package importable when run from anywhere
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
from tpu_mpi_tests.instrument.aggregate import (  # noqa: E402
    expand_rank_files,
)


def native_binary() -> Path | None:
    exe = NATIVE_DIR / "tpumt_avg"
    if not exe.exists() and not os.environ.get("TPU_MPI_TESTS_NO_NATIVE"):
        try:
            subprocess.run(
                ["make", "-C", str(NATIVE_DIR), "tpumt_avg"],
                capture_output=True,
                check=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return None
    return exe if exe.exists() else None


def python_aggregate(pattern, key, stats, files) -> int:
    print(f"PATTERN={pattern}")
    rc = 0
    for path in files:
        try:
            lines = Path(path).read_text().splitlines()
        except OSError:
            print(f"avg.py: cannot open {path}", file=sys.stderr)
            rc = 1
            continue
        vals = []
        for line in lines:
            if pattern not in line:
                continue
            if key:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if key in rec and isinstance(rec[key], (int, float)):
                    vals.append(float(rec[key]))
            else:
                parts = line.split(":")
                if len(parts) < 2:
                    continue
                try:
                    vals.append(float(parts[1].split()[0].rstrip(",;")))
                except (ValueError, IndexError):
                    continue
        if not vals:
            print(f"{path} no-matches")
            continue
        mean = sum(vals) / len(vals)
        if stats:
            print(
                f"{path} {mean:g} min={min(vals):g} max={max(vals):g} "
                f"n={len(vals)}"
            )
        else:
            print(f"{path} {mean:g}")
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pattern", "-p", default="gather")
    p.add_argument("--key", "-k", default=None,
                   help="JSONL numeric field to aggregate")
    p.add_argument("--stats", "-s", action="store_true")
    p.add_argument("--no-native", action="store_true",
                   help="force the Python fallback")
    p.add_argument("files", nargs="*", default=None)
    args = p.parse_args(argv)
    files = args.files or sorted(glob.glob("out-*.txt"))
    # multi-process runs write per-rank JSONL as base.p<i>.jsonl (see
    # instrument/report.rank_suffixed_path); expand a base path to its set
    # so `avg.py --key seconds out.jsonl` aggregates every rank's file —
    # the SAME expansion tpumt-report uses, so the two tools cannot
    # diverge on which files an argument names
    files = expand_rank_files(files)
    if not files:
        print("avg.py: no input files", file=sys.stderr)
        return 1

    if not args.no_native:
        exe = native_binary()
        if exe is not None:
            cmd = [str(exe), "-p", args.pattern]
            if args.key:
                cmd += ["-k", args.key]
            if args.stats:
                cmd.append("-s")
            return subprocess.run(cmd + files).returncode
    return python_aggregate(args.pattern, args.key, args.stats, files)


if __name__ == "__main__":
    sys.exit(main())
