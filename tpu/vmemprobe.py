#!/usr/bin/env python
"""VMEM fit-model validation against Mosaic (round 3, VERDICT r2 weak #6).

The streaming/strip kernels budget their VMEM live set with analytic
models (``_stream_live_bytes``, ``_fit_strip``'s ``rows_bytes``) that were
calibrated by incident. This tool measures Mosaic's ACTUAL scoped-vmem
allocation per kernel configuration: it compiles each config with
``compiler_params=CompilerParams(vmem_limit_bytes=1 KiB)`` — guaranteed to
fail — and parses the real requested size out of the diagnostic
("Scoped allocation with size <X> and limit 1.0K exceeded ..."), then
reports model/actual per config.

Usage (on a TPU): python tpu/vmemprobe.py [--jsonl OUT.jsonl]
Emits one JSON line per config: {config, model_bytes, actual_bytes,
ratio}; exits 1 if any config's model UNDER-estimates Mosaic (the unsafe
direction) by more than 5%.

``--jsonl`` additionally appends Reporter-compatible ``kind: "vmem"``
records (config/model_bytes/actual_bytes/ratio, manifest first) so
``tpumt-report`` renders the model-vs-actual table from the same file
set as every other run artifact instead of this tool being stdout-only.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_SIZE_RE = re.compile(r"Scoped allocation with size ([\d.]+)([KMG]?)\b")
_UNITS = {"": 1, "K": 2**10, "M": 2**20, "G": 2**30}


def _try_compile(fn, limit_bytes):
    """Compile+run ``fn`` under a scoped-vmem limit. Returns (ok,
    reported_bytes): on failure, ``reported_bytes`` is the cumulative
    stack size at the failing allocation (a lower bound on the true
    high-water mark)."""
    import jax
    from jax.experimental import pallas as pl_mod
    from jax.experimental.pallas import tpu as pltpu

    from tpu_mpi_tests.kernels import pallas_kernels as PK

    # the kernels are jax.jit-wrapped: a cached trace would freeze the
    # FIRST trial's compiler_params for every later limit
    PK.stencil2d_iterate_pallas.clear_cache()
    PK.heat2d_pallas.clear_cache()
    PK.stencil2d_pallas.clear_cache()
    PK.dual_dim_step_pallas.clear_cache()

    orig = pl_mod.pallas_call

    def patched(*a, **kw):
        kw["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=int(limit_bytes)
        )
        return orig(*a, **kw)

    pl_mod.pallas_call = patched
    try:
        jax.block_until_ready(fn())
        return True, None
    except Exception as e:  # noqa: BLE001 — the failure IS the measurement
        m = _SIZE_RE.search(str(e))
        if not m:
            raise RuntimeError(
                f"no scoped-allocation size in error: {str(e)[-500:]}"
            ) from e
        return False, int(float(m.group(1)) * _UNITS[m.group(2)])
    finally:
        pl_mod.pallas_call = orig


def measure_scoped_bytes(fn, hi=64 * 2**20, tol=64 * 2**10):
    """True scoped-vmem high-water mark of ``fn``'s kernel, by bisecting
    the minimal limit that compiles. (A single 1 KiB-limit probe is NOT
    enough: the error reports the cumulative stack at the FIRST failing
    allocation — the I/O block buffers — and misses later per-op temps,
    which is exactly what the live-set models exist to cover.)"""
    ok, reported = _try_compile(fn, 1024)
    if ok:
        raise RuntimeError("kernel compiled under a 1 KiB scoped-vmem limit?!")
    lo = reported  # the stack is at least this deep
    if not _try_compile(fn, hi)[0]:
        raise RuntimeError(f"does not fit even {hi} bytes of scoped vmem")
    while hi - lo > tol:
        mid = (lo + hi) // 2
        ok, reported = _try_compile(fn, mid)
        if ok:
            hi = mid
        else:
            lo = max(mid, reported)
    return hi


def configs():
    """(name, fn, model_bytes) triples covering every VMEM-fit model."""
    import jax
    import jax.numpy as jnp

    from tpu_mpi_tests.kernels import pallas_kernels as PK
    from tpu_mpi_tests.kernels.stencil import N_BND

    out = []
    steps = 4
    K = steps * N_BND

    # full-height dim-0 k-step iterate: model = strip · rows_bytes
    # (dtype-sized double-buffered I/O + f32-sized temps — the caller's
    # formula in stencil2d_iterate_pallas)
    for nxg, width, dtype in (
        (1028 + 2 * K, 512, jnp.float32),
        (2048 + 2 * K, 512, jnp.float32),
        (2746 + 2 * K, 8192, jnp.float32),   # the round-2 S=3 block shape
        (4096 + 2 * K, 512, jnp.float32),    # the S=2 headline block
        (2048 + 2 * K, 512, jnp.bfloat16),
    ):
        itemsize = jnp.dtype(dtype).itemsize
        name = (f"fullheight_d0_k{steps}_{nxg}x{width}_"
                f"{jnp.dtype(dtype).name}")
        try:
            rows_bytes = PK._strip_rows_bytes(nxg, itemsize)
            strip = PK._fit_strip(128, width, rows_bytes, min_strip=128,
                                  budget=PK._VMEM_BUDGET_CAL)
        except ValueError as e:
            out.append((name, None, str(e)[:200]))
            continue
        model = strip * rows_bytes

        def fn(nxg=nxg, width=width, dtype=dtype):
            z = jax.numpy.ones((nxg, width), dtype)
            return PK.stencil2d_iterate_pallas(
                z, 1e-4, dim=0, steps=steps, phys_static=(1, 1),
                stream=False,
            )

        out.append((name, fn, model))

    # row-streaming dim-0 k-step iterate: model = _stream_live_bytes
    for nx, ny, dtype in (
        (8208, 8192, jnp.float32),
        (8208, 8192, jnp.bfloat16),
    ):
        itemsize = jnp.dtype(dtype).itemsize
        sub = max(8, 8 * 4 // itemsize)
        name = f"stream_d0_k{steps}_{nx}x{ny}_{jnp.dtype(dtype).name}"
        try:
            B, P = PK._fit_stream0_blocks(
                ny, K, itemsize, sub,
                bf16_temps=PK._BF16_TEMPS_ITER_STREAM,
            )
        except ValueError as e:
            out.append((name, None, str(e)[:200]))
            continue
        model = PK._stream_live_bytes(
            B, K, P, itemsize, bf16_temps=PK._BF16_TEMPS_ITER_STREAM
        )

        def fn(nx=nx, ny=ny, dtype=dtype):
            z = jax.numpy.ones((nx, ny), dtype)
            return PK.stencil2d_iterate_pallas(
                z, 1e-4, dim=0, steps=steps, phys_static=(1, 1),
                stream=True,
            )

        out.append((name, fn, model))

    # heat row-streaming kernel (full-width blocks; _stream_live_bytes)
    for nx, ny, dtype in (
        (2056, 2056, jnp.float32),
        (2056, 2056, jnp.bfloat16),
    ):
        itemsize = jnp.dtype(dtype).itemsize
        sub = max(8, 8 * 4 // itemsize)
        name = f"heat_k{steps}_{nx}x{ny}_{jnp.dtype(dtype).name}"
        B = PK._fit_block_rows(ny, steps, itemsize, sub,
                               bf16_temps=PK._BF16_TEMPS_HEAT)
        if PK._stream_live_bytes(B, steps, ny, itemsize,
                                 bf16_temps=PK._BF16_TEMPS_HEAT) > \
                PK._VMEM_BUDGET_CAL:
            out.append((name, None, "width exceeds budget at min block"))
            continue
        model = PK._stream_live_bytes(B, steps, ny, itemsize,
                                      bf16_temps=PK._BF16_TEMPS_HEAT)

        def fn(nx=nx, ny=ny, dtype=dtype):
            z = jax.numpy.ones((nx, ny), dtype)
            return PK.heat2d_pallas(z, 0.05, 0.05, steps=steps,
                                    n_bnd=steps)

        out.append((name, fn, model))

    # one-step derivative row-streamer (stencil2d_pallas stream path) and
    # the dual-dim step kernel at bf16: round-5 CALIBRATED consumers
    # (VERDICT r4 #4) — the probe validates the per-kernel coefficients
    # the fits now run with
    for dtype in (jnp.bfloat16,):
        itemsize = jnp.dtype(dtype).itemsize
        sub = max(8, 8 * 4 // itemsize)
        name = f"derivstream_d0_16388x512_{jnp.dtype(dtype).name}"
        from tpu_mpi_tests.kernels.stencil import N_BND as NB

        try:
            B, P = PK._fit_stream0_blocks(
                512, NB, itemsize, sub,
                bf16_temps=PK._BF16_TEMPS_DERIV_STREAM,
            )
        except ValueError as e:
            out.append((name, None, str(e)[:200]))
        else:
            model = PK._stream_live_bytes(
                B, NB, P, itemsize,
                bf16_temps=PK._BF16_TEMPS_DERIV_STREAM,
            )

            def fn(dtype=dtype):
                z = jax.numpy.ones((16388, 512), dtype)
                return PK.stencil2d_pallas(z, 1e-4, dim=0)

            out.append((name, fn, model))

        name = f"dualdim_2056x2056_{jnp.dtype(dtype).name}"
        Bd = PK._fit_block_rows(2056, NB, itemsize, sub,
                                bf16_temps=PK._BF16_TEMPS_DUAL_DIM)
        model = PK._stream_live_bytes(Bd, NB, 2056, itemsize,
                                      bf16_temps=PK._BF16_TEMPS_DUAL_DIM)

        def fn2(dtype=dtype):
            z = jax.numpy.ones((2056, 2056), dtype)
            return PK.dual_dim_step_pallas(z, NB, 1.0, 1.0)

        out.append((name, fn2, model))

    # dim-1 full-width strips (lane-dim taps): model = strip · rows_bytes
    for ny, dtype in (
        (8192 + 2 * K, jnp.float32),
        (8192 + 2 * K, jnp.bfloat16),
    ):
        name = f"fullwidth_d1_k{steps}_8192x{ny}_{jnp.dtype(dtype).name}"
        try:
            # tile=64 mirrors the production bench/halo path: the round-4
            # strip re-sweep measured 64/88/96 flat within contention
            # noise at bf16 (BASELINE.md), so production keeps 64 and the
            # probe validates what production runs
            strip = PK._kstep_d1_strip(8192, ny, dtype, 64)
        except ValueError as e:
            out.append((name, None, str(e)[:200]))
            continue
        model = strip * PK._d1_strip_rows_bytes(ny, dtype)

        def fn(ny=ny, dtype=dtype):
            z = jax.numpy.ones((8192, ny), dtype)
            return PK.stencil2d_iterate_pallas(
                z, 1e-4, dim=1, steps=steps, phys_static=(1, 1), tile=64,
            )

        out.append((name, fn, model))

    return out


def _make_reporter(jsonl_path):
    """Reporter sink for ``--jsonl`` (manifest first, like every driver
    file). None when no path was asked for; manifest emission is
    best-effort — the probe's stdout contract must survive a backend
    where the manifest cannot be built."""
    if not jsonl_path:
        return None
    from tpu_mpi_tests.instrument.report import Reporter

    rep = Reporter(jsonl_path=jsonl_path)
    try:
        from tpu_mpi_tests.instrument.manifest import run_manifest

        rep.jsonl(run_manifest())
    except Exception:
        pass
    return rep


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--jsonl",
        default=None,
        metavar="PATH",
        help="append kind:'vmem' JSONL records here (tpumt-report "
        "renders them as the VMEM model-vs-actual table)",
    )
    args = ap.parse_args(argv)
    rep = _make_reporter(args.jsonl)

    def emit(rec):
        if rep is not None:
            rep.jsonl({"kind": "vmem", **rec})

    unsafe = 0
    for name, fn, model in configs():
        if fn is None:  # the fit itself rejected this hand-listed shape
            print(json.dumps({"config": name, "error": model}), flush=True)
            emit({"config": name, "error": model})
            unsafe += 1
            continue
        try:
            actual = measure_scoped_bytes(fn)
        except RuntimeError as e:
            print(json.dumps({"config": name, "error": str(e)[:200]}),
                  flush=True)
            emit({"config": name, "error": str(e)[:200]})
            unsafe += 1
            continue
        ratio = model / actual
        print(json.dumps({
            "config": name,
            "model_bytes": model,
            "actual_bytes": actual,
            "model_over_actual": round(ratio, 3),
        }), flush=True)
        emit({
            "config": name,
            "model_bytes": model,
            "actual_bytes": actual,
            "ratio": round(ratio, 3),
        })
        if ratio < 0.95:  # model under-estimates → OOM risk
            unsafe += 1
    if rep is not None:
        rep.close()
    return 1 if unsafe else 0


if __name__ == "__main__":
    sys.exit(main())
