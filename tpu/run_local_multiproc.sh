#!/bin/bash
# Multi-process distributed run on ONE machine with fake CPU devices
# (≅ `mpirun -np N` on a workstation — the dev-loop the reference lacks;
# SURVEY.md §4 "multi-node without a cluster").
#
# Each process gets 1 fake CPU device and they form a real jax.distributed
# world over localhost, exercising the same bootstrap/collective paths as a
# TPU pod.
#
# Usage: ./run_local_multiproc.sh <nprocs> <driver> [driver args...]

set -eu

if [ $# -lt 2 ]; then
  echo "Usage: $0 <nprocs> <driver> [driver args...]"
  exit 1
fi

nprocs=$1
driver=$2
shift 2

repo_dir=$(cd "$(dirname "$0")/.." && pwd)
. "$repo_dir/tpu/worldlib.sh"

rc=0
PYTHONPATH="$repo_dir${PYTHONPATH:+:$PYTHONPATH}" \
  spawn_world -o out-local- "$nprocs" \
  python -m "tpu_mpi_tests.drivers.${driver}" --fake-devices 1 "$@" \
  || rc=$?
echo "done (rc=$rc); outputs in out-local-*.txt"
exit $rc
