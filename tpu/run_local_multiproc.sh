#!/bin/bash
# Multi-process distributed run on ONE machine with fake CPU devices
# (≅ `mpirun -np N` on a workstation — the dev-loop the reference lacks;
# SURVEY.md §4 "multi-node without a cluster").
#
# Each process gets 1 fake CPU device and they form a real jax.distributed
# world over localhost, exercising the same bootstrap/collective paths as a
# TPU pod.
#
# Usage: ./run_local_multiproc.sh <nprocs> <driver> [driver args...]

set -eu

if [ $# -lt 2 ]; then
  echo "Usage: $0 <nprocs> <driver> [driver args...]"
  exit 1
fi

nprocs=$1
driver=$2
shift 2

repo_dir=$(cd "$(dirname "$0")/.." && pwd)
port=$((10000 + RANDOM % 20000))

pids=()
for ((i = 0; i < nprocs; i++)); do
  JAX_COORDINATOR_ADDRESS="localhost:${port}" \
  JAX_NUM_PROCESSES="$nprocs" \
  JAX_PROCESS_ID="$i" \
  PYTHONPATH="$repo_dir${PYTHONPATH:+:$PYTHONPATH}" \
    python -m "tpu_mpi_tests.drivers.${driver}" --fake-devices 1 "$@" \
    > "out-local-${i}.txt" 2>&1 &
  pids+=($!)
done

rc=0
for pid in "${pids[@]}"; do
  wait "$pid" || rc=$?
done
echo "done (rc=$rc); outputs in out-local-*.txt"
exit $rc
