#!/bin/bash
# TPU-pod launch wrapper (≅ summit/run.sh, /root/reference/summit/run.sh:1-32).
#
# Runs ONE worker's share of a driver; on a multi-host pod, invoke on every
# worker (e.g. `gcloud compute tpus tpu-vm ssh $TPU --worker=all --command=...`).
# jax.distributed autodetects the pod topology on TPU VMs; for manual
# coordination export JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
# JAX_PROCESS_ID first (≅ jsrun/mpirun rank wiring).
#
# Usage: ./run.sh device|managed xprof|none <driver> [extra driver args...]
#   arg1: memory space twin (≅ um|noum managed/unmanaged binaries)
#   arg2: profiler capture (≅ nsys|nvprof|none; xprof writes a trace dir
#         openable in TensorBoard/XProf)
#   arg3: driver module under tpu_mpi_tests.drivers (e.g. mpi_daxpy_nvtx,
#         stencil2d)
# Output: out-<tag>.txt in the CWD (+ out-<tag>.jsonl), aggregate with avg.py.

set -eu

if [ $# -lt 3 ]; then
  echo "Usage: $0 device|managed xprof|none <driver> [driver args...]"
  exit 1
fi

space=$1
prof=$2
driver=$3
shift 3

repo_dir=$(cd "$(dirname "$0")/.." && pwd)
out_dir=$PWD
# per-rank tag (≅ %q{PMIX_RANK} trace naming, summit/run.sh:15-19): two
# processes of a multi-process world on one host must not collide in
# out-<tag>.txt or profile/<tag> — take the launcher-provided process id
# (tpumt_run / run_local_multiproc / job.sh set JAX_PROCESS_ID; GCP TPU
# pods set TPU_WORKER_ID)
rank="${JAX_PROCESS_ID:-${TPU_WORKER_ID:-}}"
world="${JAX_NUM_PROCESSES:-}"
tag="${space}_${prof}_${driver}_$(hostname -s)"
tag="${tag}${world:+_w${world}}${rank:+_r${rank}}"

prof_args=""
if [ "$prof" == "xprof" ]; then
  mkdir -p profile
  prof_args="--profile-dir profile/${tag}"
fi

space_args=""
case "$driver" in
  mpi_daxpy_nvtx) space_args="--space ${space}" ;;
  stencil2d) if [ "$space" == "managed" ]; then space_args="--managed"; fi ;;
esac

cd "$out_dir"
PYTHONPATH="$repo_dir${PYTHONPATH:+:$PYTHONPATH}" \
  python -m "tpu_mpi_tests.drivers.${driver}" \
  $space_args $prof_args --jsonl "out-${tag}.jsonl" "$@" \
  > "out-${tag}.txt" 2>&1
echo "wrote out-${tag}.txt"
